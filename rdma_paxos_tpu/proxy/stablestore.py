"""ctypes binding to the native append-only stable store
(``native/stablestore.cpp`` — the BerkeleyDB RECNO analog of the reference's
``src/db/db-interface.c``)."""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libstablestore.so")

_lib: Optional[ctypes.CDLL] = None


def atomic_write(path: str, data: bytes, durable: bool = True) -> None:
    """Crash-safe whole-file write: tmp + rename (+ fsyncs when
    ``durable``) — a crash at any point leaves either the old complete
    file or the new complete file, never a mix. ``durable=False`` skips
    the fsyncs: the rename is still atomic against PROCESS death (abort,
    SIGKILL), just not against power loss — right for high-frequency
    recovery points whose loss only widens the recovery window. The
    single implementation for every control file (HardState, elastic
    recovery dumps)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        if durable:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if durable:
        dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                      os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)


_MAGIC = 0x52505353544F5231          # "RPSSTOR1" (stablestore.cpp)


def trimmed_dump(path: str, n: int) -> bytes:
    """Serialize records ``[base, n)`` of the store at ``path`` — used
    to reconstruct the store blob that pairs with a recovery point taken
    when the (still-live, possibly longer) store had ``n`` records. A
    compacted source yields a dump carrying the same base header."""
    import struct
    import tempfile
    src = StableStore(path)
    try:
        if n >= len(src):
            return src.dump()
        if n < src.base:
            # the store was compacted PAST the recovery point: records
            # [n, base) no longer exist, so a trimmed dump would be a
            # silent hole — fail so the caller falls back to a complete
            # recovery source
            raise OSError(
                "store compacted to %d, past recovery point %d"
                % (src.base, n))
        fd, tmp = tempfile.mkstemp(suffix=".trim")
        os.close(fd)
        os.unlink(tmp)               # ss_open creates it fresh
        dst = StableStore(tmp)
        try:
            if src.base:
                # adopt the source's base (empty-store header load)
                dst.load(struct.pack("<QQ", _MAGIC, src.base))
            for i in range(src.base, n):
                dst.append(src.read(i))
            return dst.dump()
        finally:
            dst.close()
            try:
                os.unlink(tmp)
            except OSError:
                pass
    finally:
        src.close()


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        subprocess.run(["make", "-C", _NATIVE_DIR, "libstablestore.so"],
                       check=True, capture_output=True)
    lib = ctypes.CDLL(_LIB_PATH)
    lib.ss_open.restype = ctypes.c_void_p
    lib.ss_open.argtypes = [ctypes.c_char_p]
    lib.ss_append.restype = ctypes.c_int64
    lib.ss_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint32]
    lib.ss_append_many.restype = ctypes.c_int64
    lib.ss_append_many.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64]
    lib.ss_sync.restype = ctypes.c_int
    lib.ss_sync.argtypes = [ctypes.c_void_p]
    lib.ss_count.restype = ctypes.c_int64
    lib.ss_count.argtypes = [ctypes.c_void_p]
    lib.ss_base.restype = ctypes.c_int64
    lib.ss_base.argtypes = [ctypes.c_void_p]
    lib.ss_compact.restype = ctypes.c_int64
    lib.ss_compact.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.ss_read.restype = ctypes.c_int64
    lib.ss_read.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                            ctypes.c_char_p, ctypes.c_uint32]
    lib.ss_dump_len.restype = ctypes.c_int64
    lib.ss_dump_len.argtypes = [ctypes.c_void_p]
    lib.ss_dump.restype = ctypes.c_int64
    lib.ss_dump.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_uint64]
    lib.ss_load.restype = ctypes.c_int64
    lib.ss_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_uint64]
    lib.ss_reset.restype = ctypes.c_int
    lib.ss_reset.argtypes = [ctypes.c_void_p]
    lib.ss_close.restype = None
    lib.ss_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class HardState:
    """Durable election state ``(term, voted_term, voted_for)``.

    The reference makes votes durable by replicating them to a majority's
    memory before acking (``rc_replicate_vote``, ``dare_ibv_rc.c:1049``)
    and reading them back on recovery (``rc_get_replicated_vote``). Here
    the device step replicates the pair to live peers' ``vote_rec_*``
    state; this file is the host-side persistence layer the driver writes
    between steps, so a crash-recovered replica restores
    ``max(peer records, this file)``.
    Atomic: temp file + fsync + rename + directory fsync.

    Durability window: the pair is persisted AFTER the step in which the
    vote was gathered and counted, so for that one step the vote exists
    only in live peers' volatile ``vote_rec_*`` memory — the same
    guarantee as the reference, whose ``rc_replicate_vote`` also writes
    only into a majority's volatile remote memory (``dare_ibv_rc.c:1049``);
    recovery therefore always consults the peer records AND this file
    (``recover_vote``), and a whole-cluster power loss inside that window
    is outside both designs' fault model."""

    def __init__(self, path: str):
        self.path = path
        self._last = None

    def save(self, term: int, voted_term: int, voted_for: int) -> None:
        tup = (int(term), int(voted_term), int(voted_for))
        if tup == self._last:
            return
        atomic_write(self.path, np.array(tup, "<i8").tobytes())
        self._last = tup

    def load(self):
        """-> (term, voted_term, voted_for) or None if absent/corrupt."""
        try:
            with open(self.path, "rb") as f:
                b = f.read()
        except FileNotFoundError:
            return None
        if len(b) != 24:
            return None
        t = np.frombuffer(b, "<i8")
        return (int(t[0]), int(t[1]), int(t[2]))


class StableStore:
    """Append-only record store; every committed socket event is persisted
    in log order (store_record analog, db-interface.c:65-96), and the whole
    store serializes into one buffer for joiner snapshot transfer
    (dump_records :98-134)."""

    def __init__(self, path: str):
        self._lib = _load()
        self.path = path
        # host-side progress accounting for health snapshots / metrics
        # (this wrapper is the single append doorway, so counting here
        # covers every record): records/bytes appended through THIS
        # handle since open — the durable truth stays in the file
        self.appended_records = 0
        self.appended_bytes = 0
        self.syncs = 0
        self._h = self._lib.ss_open(path.encode())
        if not self._h:
            raise OSError(f"cannot open stable store at {path}")

    def _handle(self):
        """Live native handle; use-after-close raises instead of handing
        ctypes a NULL to segfault on (e.g. a second driver.stop()).
        NOT a concurrency guard: a thread that read the handle before a
        concurrent close() still races — callers must sequence close()
        after their worker threads exit (ClusterDriver.stop refuses to
        close under a live poll thread for exactly this reason)."""
        h = self._h
        if not h:
            raise ValueError("stable store is closed")
        return h

    def append(self, record: bytes) -> int:
        idx = self._lib.ss_append(self._handle(), record, len(record))
        if idx < 0:
            raise OSError("stable store append failed")
        self.appended_records += 1
        self.appended_bytes += len(record)
        return idx

    def append_framed(self, blob: bytes) -> int:
        """Append a PRE-FRAMED batch (([u32 len][bytes])*) — the zero-
        copy hot path fed by SimCluster's vectorized window decode."""
        if not blob:
            return 0
        n = self._lib.ss_append_many(self._handle(), blob, len(blob))
        if n < 0:
            raise OSError("stable store framed append failed")
        self.appended_records += int(n)
        self.appended_bytes += len(blob)
        return int(n)

    def sync(self) -> None:
        if self._lib.ss_sync(self._handle()) != 0:
            raise OSError("fdatasync failed")
        self.syncs += 1

    def stats(self) -> dict:
        """Health-snapshot summary: absolute record count, compaction
        base, bytes/records appended through this handle, fdatasync
        count, and the backing file size. Safe on a CLOSED store (the
        post-stop ``driver.health()`` call is exactly the post-mortem
        this feeds): native-handle reads degrade to -1 instead of
        raising."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            size = -1
        try:
            records, base = len(self), self.base
        except ValueError:           # handle closed
            records, base = -1, -1
        return dict(records=records, base=base,
                    appended_records=self.appended_records,
                    appended_bytes=self.appended_bytes,
                    syncs=self.syncs, file_bytes=size)

    def __len__(self) -> int:
        """ABSOLUTE record count (base + retained) — indices are stable
        across compaction."""
        return int(self._lib.ss_count(self._handle()))

    @property
    def base(self) -> int:
        """Absolute index of the first retained record (0 unless
        compacted): records below it were dropped after an app-state
        checkpoint covered their effects."""
        return int(self._lib.ss_base(self._handle()))

    def compact(self, upto: int) -> int:
        """Drop records below absolute index ``upto`` (crash-safe
        rewrite+rename). The caller must hold an app-state checkpoint
        taken at exactly ``upto`` — a fresh app is rebuilt as
        checkpoint + replay of [upto, len))."""
        b = self._lib.ss_compact(self._handle(), upto)
        if b < 0:
            raise OSError("stable store compaction failed")
        return int(b)

    def read(self, idx: int, cap: int = 1 << 20) -> bytes:
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.ss_read(self._handle(), idx, buf, cap)
        if n < 0:
            raise IndexError(idx)
        return buf.raw[:min(n, cap)]

    def dump(self) -> bytes:
        n = self._lib.ss_dump_len(self._handle())
        buf = ctypes.create_string_buffer(max(int(n), 1))
        w = self._lib.ss_dump(self._handle(), buf, n)
        if w < 0:
            raise OSError("dump failed")
        return buf.raw[:w]

    def reset(self) -> None:
        """Discard all records (pre-snapshot-load; ss_load appends, so a
        reload without reset would duplicate history)."""
        if self._lib.ss_reset(self._handle()) != 0:
            raise OSError("reset failed")

    def load(self, blob: bytes) -> int:
        n = self._lib.ss_load(self._handle(), blob, len(blob))
        if n < 0:
            raise OSError("malformed dump")
        return int(n)

    def close(self) -> None:
        if self._h:
            self._lib.ss_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
