"""Proxy: the RSM client + replay engine (reference ``src/proxy/proxy.c``).

Leader side: every socket event the interposition shim reports (CONNECT /
SEND / CLOSE) is tagged with a cluster-wide connection id
(``node_id << 8 | counter`` — proxy.c:101-106), queued for the driver to
batch into the consensus step, and the shim's blocking ack is released only
once the entry is committed + applied (the spin at proxy.c:160, here a
``threading.Event``).

Follower side: committed events whose connection id originates at another
node are replayed into the local unmodified app over loopback TCP
(``do_action_connect/send/close``, proxy.c:373-439) — producing the
identical byte stream the leader's app consumed.

The shim ↔ driver wire protocol is defined in ``native/interpose.cpp``.
"""

from __future__ import annotations

import contextlib
import functools
import os
import socket
import struct
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.obs import trace as obs_trace
from rdma_paxos_tpu.obs.metrics import default_registry
from rdma_paxos_tpu.obs.trace import default_ring

OP_HELLO, OP_CONNECT, OP_SEND, OP_CLOSE = 1, 2, 3, 4

# one-shot stderr warning latch for unverifiable quiesce barriers (the
# structured signal — quiesce_unknown trace event + counter — fires on
# every occurrence; the human-readable line only once per process)
_QUIESCE_UNKNOWN_WARNED = False


def spec_send_refused_dirty(etype: int, conn_id: int, replicated_conns,
                            proxy, app_dirty: bool) -> bool:
    """Shared intake-refusal quarantine policy (single source for BOTH
    runtimes — ClusterDriver and NodeDaemon — so they cannot drift).

    True iff refusing this event with -1 leaves a SPECULATIVE app
    diverged: the shim already delivered a SEND's bytes to the app
    (read() returns before the verdict), so a refused SEND on a
    replicated session means the app executed input that will never
    commit — the caller must set ``app_dirty`` before severing, exactly
    as failing in-flight events does."""
    return (etype == int(EntryType.SEND)
            and conn_id in replicated_conns
            and proxy is not None
            and proxy.spec_mode and not app_dirty)

_OP_TO_ETYPE = {
    OP_CONNECT: EntryType.CONNECT,
    OP_SEND: EntryType.SEND,
    OP_CLOSE: EntryType.CLOSE,
}


@dataclass
class PendingEvent:
    """One shim event awaiting commit (the blocked app thread's handle).

    Two completion surfaces: ``done`` (a threading.Event for in-process
    waiters) and an optional ``on_done`` callback the ProxyServer
    attaches to send the seq-tagged wire response — the pipelined-shim
    contract, where the link thread never blocks on a commit."""

    etype: EntryType
    conn_id: int
    payload: bytes
    done: threading.Event = field(default_factory=threading.Event)
    status: int = 0
    on_done: Optional[Callable[[int], None]] = None
    _cb_lock: threading.Lock = field(default_factory=threading.Lock)
    # creation timestamp (perf_counter): release-site instrumentation
    # measures intake→commit-release as the client-visible commit
    # latency (obs commit_latency_seconds histogram)
    t0: float = field(default_factory=time.perf_counter)

    def release(self, status: int = 0) -> None:
        self.status = status
        self.done.set()
        self._fire()

    def attach(self, cb: Callable[[int], None]) -> None:
        """Attach the wire-response callback (fires immediately if the
        event already completed — release/attach may race)."""
        with self._cb_lock:
            self.on_done = cb
        if self.done.is_set():
            self._fire()

    def _fire(self) -> None:
        with self._cb_lock:
            if not self.done.is_set() or self.on_done is None:
                return
            cb, self.on_done = self.on_done, None
        try:
            cb(self.status)
        except OSError:
            pass                     # link died: the shim fell back


class ProxyServer:
    """Unix-socket server the interposed app connects to.

    One thread per app link. The link thread only READS: each event is
    handed to the driver-provided ``on_event`` callback, and the
    seq-tagged response is written either immediately (pass-through /
    sever verdicts) or from whatever thread releases the PendingEvent
    once the entry commits — so many app threads can have events in
    flight concurrently (the reference's tailq-insert-then-spin split,
    ``proxy.c:114-160``). Per-fd event order is preserved end-to-end:
    the shim serializes writes under its send mutex and this server
    reads them in order into the driver's submit queue.
    """

    def __init__(self, sock_path: str, node_id: int,
                 on_event: Callable[[int, int, bytes],
                                    Optional[PendingEvent]],
                 conn_ctr_start: int = 0, obs=None):
        self.sock_path = sock_path
        # Observability facade (rdma_paxos_tpu.obs) — link threads
        # count wire events per op so replication throughput and shim
        # pressure export with every snapshot
        self.obs = obs
        # conn ids pack the origin into bits 24+ of an int32 log column
        # (M_CONN): an id >= 128 would flip the sign bit and break the
        # origin test ((conn >> 24) == host_id) everywhere downstream —
        # fail loudly here rather than hang that host's clients. Elastic
        # host ids grow monotonically, so long-lived deployments must
        # recycle ids below 128 (the reference packs node_id<<8 into an
        # int with the same kind of bound, proxy.c:101-106).
        if not 0 <= node_id < 128:
            raise ValueError(
                f"node_id {node_id} does not fit the conn-id origin "
                "field (int32 M_CONN allows 0..127)")
        self.node_id = node_id
        self.on_event = on_event
        # declared by the shim's HELLO (bit0 of its payload byte): the
        # app executes SPECULATIVELY on not-yet-committed input, holding
        # replies until commit (output commit). The driver needs this to
        # know that failing an inflight event (deposition) leaves the
        # app DIRTY — it consumed input that may never commit — and must
        # be quarantined until rebuilt from the committed store.
        self.spec_mode = False
        # namespaced start (elastic generations) so a restarted host's
        # fresh connection ids avoid ids its previous incarnation stamped
        # into carried-over log entries. The namespace is bounded (16
        # generations x 2^20 connections before wrap), so collisions are
        # rare, not impossible — the ReplayEngine treats a repeated
        # CONNECT for a known id as a stream RESET, which keeps a wrap
        # benign (M_GEN protects the ack path independently).
        self._conn_ctr = conn_ctr_start & 0xFFFFFF
        self.conn_of_fd: Dict[Tuple[int, int], int] = {}  # (link, fd) -> id
        if os.path.exists(sock_path):
            os.unlink(sock_path)
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(sock_path)
        self._srv.listen(8)
        self._links: List[socket.socket] = []
        self._link_ctr = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def next_conn_id(self) -> int:
        self._conn_ctr = (self._conn_ctr + 1) & 0xFFFFFF
        return (self.node_id << 24) | self._conn_ctr

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                link, _ = self._srv.accept()
            except OSError:
                return
            self._links.append(link)
            lid = self._link_ctr
            self._link_ctr += 1
            threading.Thread(target=self._serve_link, args=(link, lid),
                             daemon=True).start()

    def _recv_exact(self, sock: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _serve_link(self, link: socket.socket, lid: int) -> None:
        wlock = threading.Lock()     # responses come from many threads

        def respond(seq: int, status: int) -> None:
            with wlock:
                link.sendall(struct.pack("<Ii", seq, status))

        try:
            while not self._stop.is_set():
                hdr = self._recv_exact(link, 13)
                if hdr is None:
                    return
                op, seq, fd, ln = struct.unpack("<BIiI", hdr)
                payload = self._recv_exact(link, ln) if ln else b""
                if payload is None:
                    return
                if self.obs is not None:
                    self.obs.metrics.inc("proxy_wire_events_total",
                                         replica=self.node_id, op=op)
                if op not in _OP_TO_ETYPE:       # HELLO / unknown
                    if op == OP_HELLO and payload:
                        self.spec_mode = bool(payload[0] & 1)
                    respond(seq, 0)
                    continue
                if op == OP_CONNECT:
                    self.conn_of_fd[(lid, fd)] = self.next_conn_id()
                conn_id = self.conn_of_fd.get((lid, fd), 0)
                if op == OP_CLOSE:
                    self.conn_of_fd.pop((lid, fd), None)
                # handler returns: None => pass through (0);
                # int => immediate status (<0 severs the connection);
                # PendingEvent => respond when committed (the link
                # thread moves on to the next event immediately)
                ev = self.on_event(int(_OP_TO_ETYPE[op]), conn_id,
                                   payload)
                if isinstance(ev, PendingEvent):
                    ev.attach(functools.partial(respond, seq))
                elif isinstance(ev, int):
                    respond(seq, ev)
                else:
                    respond(seq, 0)
        except OSError:
            pass
        finally:
            try:
                link.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        for l in self._links:
            try:
                l.close()
            except OSError:
                pass
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)


def replay_store_into(store, replay: "ReplayEngine",
                      start: int = 0) -> None:
    """Replay the stable store's event history from record ``start``
    into the local app (``proxy_apply_db_snapshot`` analog,
    ``proxy.c:306-339``) — the single decoder of the store record layout
    (1-byte etype + 4-byte little-endian conn id + payload). ``start=0``
    rebuilds a FRESH app; a nonzero ``start`` delivers only the delta to
    a LIVE app that already executed the prefix (store streams are
    prefix-consistent: every store is a prefix of the committed event
    order)."""
    if replay is None:
        return
    base = getattr(store, "base", 0)
    if start < base:
        # records below base were compacted away; their effects must
        # already be covered by a restored app-state checkpoint
        start = base
    for i in range(start, len(store)):
        rec = store.read(i)
        replay.apply(rec[0], int.from_bytes(rec[1:5], "little"), rec[5:])
    replay.drain_responses()


class ReplayEngine:
    """Replays committed remote-origin events into the local app over
    loopback TCP (the follower half of the reference proxy)."""

    def __init__(self, app_host: str, app_port: int):
        self.addr = (app_host, app_port)
        self.conns: Dict[int, socket.socket] = {}
        # local (ephemeral) ports of our replay sockets: the driver uses
        # these to recognize its own replayed connections arriving back
        # through the app's interposition shim
        self.local_ports: set = set()

    def _connect(self, conn_id: int) -> socket.socket:
        # a CONNECT for an id we already track means the id wrapped
        # around (bounded namespaces); the new stream replaces the old
        # one — reset rather than interleave bytes into a stale socket
        old = self.conns.pop(conn_id, None)
        if old is not None:
            try:
                self.local_ports.discard(old.getsockname()[1])
                old.close()
            except OSError:
                pass
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        # bind first so the local port is REGISTERED before the app can
        # possibly observe the connection: a hot-polling app accepts and
        # reports CONNECT to the driver concurrently with (even before)
        # our connect() returning, and the driver must never misclassify
        # our own replay connection as a client session
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        self.local_ports.add(port)
        try:
            s.connect(self.addr)
        except OSError:
            self.local_ports.discard(port)
            s.close()
            raise
        self.conns[conn_id] = s
        return s

    def apply(self, etype: int, conn_id: int, payload: bytes) -> None:
        if etype == int(EntryType.CONNECT):
            self._connect(conn_id)
        elif etype == int(EntryType.SEND):
            s = self.conns.get(conn_id)
            if s is None:       # joined mid-stream: open lazily
                s = self._connect(conn_id)
            s.sendall(payload)
        elif etype == int(EntryType.CLOSE):
            s = self.conns.pop(conn_id, None)
            if s is not None:
                try:
                    self.local_ports.discard(s.getsockname()[1])
                    s.close()
                except OSError:
                    pass

    @contextlib.contextmanager
    def raw_conn(self):
        """Context manager: a passthrough-registered connection to the
        local app for OUT-OF-BAND operations (app checkpoint dump /
        restore). Bound before connecting so the driver always
        classifies it as our own (never replicates its traffic); the
        port registration is dropped on exit so a later real client
        reusing the ephemeral port cannot be misclassified."""
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        self.local_ports.add(port)
        try:
            s.connect(self.addr)
            yield s
        finally:
            self.local_ports.discard(port)
            try:
                s.close()
            except OSError:
                pass

    def barrier(self, probe_fn, timeout: float = 10.0) -> None:
        """PROCESSED-INPUT barrier: replay input is delivered over
        per-connection sockets asynchronously, so a single-threaded
        event-loop app may service a later out-of-band connection (e.g.
        a checkpoint dump) before draining replay bytes still buffered
        on other connections. ``probe_fn(sock)`` must issue a
        request/response roundtrip on ``sock`` and return only once it
        has observed the response to ITS OWN request (discarding any
        buffered responses to earlier replayed commands). A reply on a
        connection proves the app consumed every byte written to that
        connection before the probe (TCP ordering + in-order reads), so
        probing every replay connection proves all delivered records
        were consumed."""
        for s in list(self.conns.values()):
            s.settimeout(timeout)
            try:
                probe_fn(s)
            finally:
                s.settimeout(None)

    # both address families: a dual-stack or v6-bound app's loopback
    # sockets appear in tcp6 (with v4-mapped peers), invisible to the
    # IPv4 table — scanning only /proc/net/tcp silently weakened the
    # barrier there (ADVICE.md #2)
    _PROC_TCP_PATHS = ("/proc/net/tcp", "/proc/net/tcp6")

    def _quiesce_unknown(self, reason: str) -> None:
        """The kernel-queue barrier could not be VERIFIED (unreadable
        /proc tables, failed ioctl with no compensating peer check):
        record it as unknown — never as quiescent. Logged once per
        process (stderr); traced/counted on every occurrence."""
        default_ring().record(obs_trace.QUIESCE_UNKNOWN, reason=reason)
        default_registry().inc("quiesce_unknown_total")
        global _QUIESCE_UNKNOWN_WARNED
        if not _QUIESCE_UNKNOWN_WARNED:
            _QUIESCE_UNKNOWN_WARNED = True
            print("ReplayEngine.quiesce: cannot verify kernel queues "
                  f"({reason}); treating as NOT quiescent — supply an "
                  "app_snapshot probe_fn for an exact barrier",
                  file=sys.stderr, flush=True)

    def quiesce(self, timeout: float = 5.0,
                settle_rounds: int = 3) -> bool:
        """Best-effort app-agnostic barrier (used when no probe hook is
        configured): wait until every replay connection's bytes have
        left BOTH kernel queues — our unsent send queue (TIOCOUTQ) and
        the app-side receive queue (via /proc/net/tcp{,6} rx_queue for
        the loopback peer socket) — over ``settle_rounds`` consecutive
        samples. NARROWS but does NOT close the race: bytes the app has
        read() into userspace buffers (or lines applied one at a time
        between lock releases) are invisible to kernel queues, so a
        checkpoint can still observe partially-applied input. Apps that
        can express a request/response no-op should supply the
        app_snapshot probe_fn, which is exact.

        Unverifiable is UNKNOWN, never 'empty' (the old behavior
        silently counted both a failed TIOCOUTQ ioctl and an unreadable
        /proc/net/tcp as empty, degrading the barrier to nothing on
        IPv6 loopback / non-Linux / sandboxed kernels — ADVICE.md #2):

        * no readable /proc/net/tcp{,6} table → return False (the
          app-side rx queue is unknowable);
        * TIOCOUTQ unsupported (e.g. sandboxed kernels) → degrade to
          requiring the peer-rx check to VERIFY every replay port (a
          matching row with rx_queue 0 in a readable table); if any
          port cannot be matched, return False.

        Both degradations log once per process and emit a
        ``quiesce_unknown`` trace event + counter so the weakened
        barrier is visible, and a returned False makes the caller
        abort the checkpoint instead of compacting records the
        checkpoint may not cover."""
        import fcntl
        import struct
        import termios
        import time as _time
        deadline = _time.monotonic() + timeout
        app_port = self.addr[1]
        quiet = 0
        while True:
            busy = False
            sendq_verified = True
            ports = {}
            n_conns = 0
            for s in list(self.conns.values()):
                n_conns += 1
                try:
                    out = struct.unpack(
                        "i", fcntl.ioctl(s.fileno(), termios.TIOCOUTQ,
                                         b"\x00" * 4))[0]
                except OSError:
                    # unknown, NOT empty: fall through to the peer-rx
                    # check, which must then verify this socket
                    sendq_verified = False
                    out = 0
                if out:
                    busy = True
                    break
                try:
                    ports[s.getsockname()[1]] = True
                except OSError:
                    pass
            if not busy and n_conns:
                # peer (app-side) sockets: local == app port, remote ==
                # one of our replay ports; rx_queue is hex field 4 after
                # the colon — same field layout in tcp and tcp6 (the
                # address is longer, the :port suffix parse is
                # identical)
                readable = 0
                matched = set()
                for proc in self._PROC_TCP_PATHS:
                    try:
                        with open(proc) as f:
                            lines = f.readlines()[1:]
                    except OSError:
                        continue     # this table unreadable
                    readable += 1
                    for ln in lines:
                        try:
                            parts = ln.split()
                            lport = int(parts[1].split(":")[1], 16)
                            rport = int(parts[2].split(":")[1], 16)
                            if lport == app_port and rport in ports:
                                rxq = int(parts[4].split(":")[1], 16)
                                matched.add(rport)
                                if rxq:
                                    busy = True
                                    break
                        except (IndexError, ValueError):
                            continue  # garbled row: not a verification
                    if busy:
                        break
                if readable == 0:
                    self._quiesce_unknown(
                        "no readable /proc/net/tcp{,6}")
                    return False
                if (not busy and not sendq_verified
                        and (len(matched) < n_conns
                             or len(ports) < n_conns)):
                    # the send queue was unverifiable AND at least one
                    # replay socket has no visible peer row: nothing
                    # proves its bytes were consumed
                    self._quiesce_unknown(
                        "TIOCOUTQ unsupported and peer rows missing "
                        f"({len(matched)}/{n_conns} verified)")
                    return False
            if not busy:
                quiet += 1
                if quiet >= settle_rounds:
                    return True
            else:
                quiet = 0
            if _time.monotonic() >= deadline:
                return False
            _time.sleep(0.002)

    def drain_responses(self) -> None:
        """The local app writes responses to replayed connections; nobody
        reads them (the reference's follower likewise discards app output
        — only the leader's app talks to real clients). Drain so the app
        never blocks on a full socket buffer."""
        for s in self.conns.values():
            s.setblocking(False)
            try:
                while s.recv(65536):
                    pass
            except (BlockingIOError, OSError):
                pass
            finally:
                s.setblocking(True)

    def close(self) -> None:
        for s in self.conns.values():
            try:
                s.close()
            except OSError:
                pass
        self.conns.clear()
