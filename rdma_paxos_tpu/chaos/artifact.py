"""Self-contained reproducer artifacts for chaos/fuzz failures.

Before this subsystem, a fuzz failure printed ``(seed, step, replica)``
and nothing else — no way to replay the failing schedule. A reproducer
artifact is ONE JSON file holding everything a replay needs plus the
post-mortem evidence an operator wants:

* ``seed`` + ``schedule`` (the FaultSchedule's JSON events, or the
  fuzzer's recorded action list) — enough to re-run deterministically;
* ``history`` — the client-op history as JSONL (when a KVS workload
  ran);
* ``trace`` — the obs trace ring dump (protocol-event post-mortem);
* ``metrics`` — the metrics registry snapshot;
* ``spans`` — the causal span dump (obs.spans): every traced
  command's submit→append→quorum→commit→apply→ack timeline with its
  ``(term, index)`` correlation — feed it to
  ``python -m rdma_paxos_tpu.obs.spans`` for a Perfetto view of the
  violation;
* ``violation`` / ``reason`` — what failed.

Written atomically (tmp + rename, same discipline as
``TraceRing.dump_on_failure``) so a crashing harness never leaves a
truncated artifact.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional

_SCHEMA = 1


def write_reproducer(path: Optional[str] = None, *, seed: int,
                     schedule, reason: str,
                     config: Optional[dict] = None,
                     history: Optional[str] = None,
                     violation: Optional[dict] = None,
                     obs=None, extra: Optional[dict] = None) -> str:
    """Persist a reproducer; returns the path (auto-generated under the
    system temp dir when ``path`` is None — callers embed it in their
    assertion message so a CI failure is replayable from the log line).

    ``schedule`` may be a FaultSchedule, a JSON string, or a plain
    list; ``history`` is a JSONL string; ``obs`` an Observability
    facade (defaults to the process-global one so module-level
    instrumentation is captured too)."""
    if obs is None:
        from rdma_paxos_tpu.obs import default
        obs = default()
    if hasattr(schedule, "events"):
        schedule = schedule.events
    elif isinstance(schedule, str):
        schedule = json.loads(schedule)
    doc = dict(
        schema=_SCHEMA,
        reason=reason,
        seed=seed,
        config=config or {},
        schedule=schedule,
        history=history,
        violation=violation,
        trace=obs.trace.dump(),
        metrics=obs.metrics.snapshot(),
        spans=(obs.spans.dump()
               if getattr(obs, "spans", None) is not None else None),
        extra=extra or {},
    )
    tctx = getattr(obs, "tracectx", None)
    if tctx is not None:
        traces = tctx.dump()
        if traces["traces"]:
            # subsystem traces (txn/topology/watch) ride along only
            # when some were recorded — trace-free artifacts keep the
            # schema-1 shape byte-for-byte
            doc["traces"] = traces
    if path is None:
        fd, path = tempfile.mkstemp(prefix="chaos_repro_",
                                    suffix=".json")
        os.close(fd)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, path)
    return path


def load_reproducer(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != _SCHEMA:
        raise ValueError(f"unknown reproducer schema: "
                         f"{doc.get('schema')!r}")
    return doc
