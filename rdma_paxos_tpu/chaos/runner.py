"""Nemesis runner: workload × fault schedule × invariants × checker.

Composes the whole chaos subsystem against a live ``SimCluster`` +
``ReplicatedKVS``: a seeded client workload (sessioned PUT/RM with
retransmit-on-failover and seeded in-flight message duplication,
linearizable read-index GETs at the leader, weak GETs anywhere) runs
under a seeded :class:`~rdma_paxos_tpu.chaos.faults.FaultSchedule`
while every step is checked against the I1–I5 protocol invariants and
the full client history is recorded; after the run settles, the
per-key Wing–Gong checker verdicts the client-visible contract.

Determinism: ALL randomness derives from the run seed (schedule,
workload, link model, timers); time is the logical step counter. The
same seed therefore yields a byte-identical schedule, history, and
verdict — the reproducibility contract ``tests/test_chaos.py`` pins.

On any violation the runner dumps a self-contained reproducer artifact
(seed, schedule JSON, history JSONL, obs trace ring, metrics snapshot)
and puts its path in the verdict; :meth:`NemesisRunner.replay` re-runs
an artifact end to end.

Fanout guard (never die mid-run): ``fanout='psum'`` cannot model
partitions — ``SimCluster.partition()``/non-full masks raise mid-step
by design. The runner refuses mask-affecting schedules on psum
clusters AT CONSTRUCTION, or — with ``skip_incompatible_faults=True``
— strips them with a single warning line and runs the rest.
"""

from __future__ import annotations

import logging
import random
from typing import Dict, List, Optional

from rdma_paxos_tpu.chaos import artifact as chaos_artifact
from rdma_paxos_tpu.chaos.faults import (
    FaultSchedule, HardStateTracker, LinkModel, StepTimerModel,
    generate_schedule)
from rdma_paxos_tpu.chaos.history import HistoryRecorder
from rdma_paxos_tpu.chaos.invariants import (
    InvariantChecker, InvariantViolation)
from rdma_paxos_tpu.chaos.linearize import check_history
from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.models.replicated_kvs import ReplicatedKVS
from rdma_paxos_tpu.obs import Observability, trace as obs_trace
from rdma_paxos_tpu.runtime.sim import SimCluster

log = logging.getLogger("rdma_paxos_tpu.chaos")

# same geometry as tests/test_replicated_kvs.py so compiled steps are
# shared across the suite (KVS commands are CMD_W*4 = 68 bytes — they
# must fit one slot)
DEFAULT_KV_CFG = LogConfig(n_slots=128, slot_bytes=128,
                           window_slots=32, batch_slots=16)


def _leader_of(res) -> int:
    """Highest-term self-claimed leader (the driver's view rule): an
    isolated deposed leader can still claim, but terms are unique per
    leader by quorum election, so max-term picks the real one."""
    if res is None:
        return -1
    claims = [(int(res["term"][r]), r) for r in range(len(res["role"]))
              if int(res["role"][r]) == int(Role.LEADER)]
    return max(claims)[1] if claims else -1


class _Workload:
    """Seeded closed-loop clients over a ReplicatedKVS.

    Each client keeps AT MOST ONE write outstanding (the
    ``ClientSession`` protocol contract) and retransmits it — to the
    new leader after a failover — until its commit is observed or the
    client gives up (→ ambiguous). With probability ``dup_msg_p`` the
    network duplicates a client message in flight: the copy is
    re-submitted a few steps later with the SAME ``(client, req_id)``
    stamp — exactly the hazard the dedup registry exists for, and the
    signal the linearizability checker uses to catch a broken one."""

    def __init__(self, kv: ReplicatedKVS, history: HistoryRecorder,
                 seed: int, n_clients: int, n_keys: int, *,
                 p_write: float = 0.45, p_rm: float = 0.12,
                 p_read: float = 0.5, p_weak: float = 0.3,
                 dup_msg_p: float = 0.15, dup_delay: int = 4,
                 patience: int = 14, p_holder_read: float = 0.35,
                 p_follower_read: float = 0.35,
                 read_patience: int = 12):
        self.kv = kv
        self.h = history
        self.rng = random.Random(f"workload:{seed}")
        # the read-path mix (leases + read-index follower reads,
        # runtime/reads.py) draws from its OWN seeded rng so enabling
        # it never perturbs the write/weak-read sequences existing
        # seeds pin
        self.rng_reads = random.Random(f"reads:{seed}")
        self.p_holder_read = p_holder_read
        self.p_follower_read = p_follower_read
        self.read_patience = read_patience
        self.sessions = [kv.session(i + 1) for i in range(n_clients)]
        self.keys = [b"key%d" % i for i in range(n_keys)]
        self.outstanding: List[Optional[dict]] = [None] * n_clients
        self.dup_queue: List[dict] = []   # in-flight duplicated msgs
        self.p_write, self.p_rm = p_write, p_rm
        self.p_read, self.p_weak = p_read, p_weak
        self.dup_msg_p, self.dup_delay = dup_msg_p, dup_delay
        self.patience = patience
        self._vn = 0

    # ---- completion observation (after the step) ----

    def observe(self, t: int, leader: int) -> None:
        if leader < 0:
            return
        self.kv._fold(leader)
        marks = self.kv.last_req[leader]
        spans = self.kv._spans()
        for ci, out in enumerate(self.outstanding):
            if out is None:
                continue
            if marks.get(out["client"], 0) >= out["req_id"]:
                self.h.ok(out["op_id"])
                if spans is not None:
                    # the client observed its commit: the span's ack
                    spans.ack_key(out["client"], out["req_id"])
                self.outstanding[ci] = None

    # ---- issue phase (before the step) ----

    def _submit(self, sess, leader: int, out: dict) -> None:
        if out["kind"] == "put":
            self.kv.put(leader, out["key"], out["val"],
                        client_id=out["client"], req_id=out["req_id"])
        else:
            self.kv.remove(leader, out["key"],
                           client_id=out["client"],
                           req_id=out["req_id"])

    def _maybe_dup(self, t: int, out: dict) -> None:
        if self.rng.random() < self.dup_msg_p:
            self.dup_queue.append(dict(
                at=t + self.rng.randint(1, self.dup_delay), **out))

    def issue(self, t: int, leader: int, down) -> None:
        # network-duplicated copies land at whatever leader now rules
        due = [d for d in self.dup_queue if d["at"] <= t]
        self.dup_queue = [d for d in self.dup_queue if d["at"] > t]
        for d in due:
            if leader >= 0:
                self._submit(None, leader, d)
                self.h.retransmit(d["op_id"], replica=leader,
                                  network_dup=True)
        for ci, sess in enumerate(self.sessions):
            out = self.outstanding[ci]
            if out is not None:
                if t - out["issued"] > self.patience:
                    # fate unknown — ambiguous for the checker
                    self.h.timeout(out["op_id"])
                    spans = self.kv._spans()
                    if spans is not None:
                        spans.fail_key(out["client"], out["req_id"],
                                       status="timeout")
                    self.outstanding[ci] = None
                elif leader >= 0 and leader != out["to"]:
                    # failover: retransmit the SAME req_id elsewhere
                    out["to"] = leader
                    self._submit(sess, leader, out)
                    self.h.retransmit(out["op_id"], replica=leader)
                    self._maybe_dup(t, out)
                out = self.outstanding[ci]
            if out is None and leader >= 0 \
                    and self.rng.random() < self.p_write:
                key = self.rng.choice(self.keys)
                if self.rng.random() < self.p_rm:
                    rid = sess.remove(leader, key)
                    kind, val = "rm", None
                else:
                    self._vn += 1
                    val = b"c%dv%d" % (sess.client_id, self._vn)
                    rid = sess.put(leader, key, val)
                    kind = "put"
                op_id = self.h.op_id_for(sess.client_id, rid)
                rec = dict(op_id=op_id, kind=kind, key=key, val=val,
                           client=sess.client_id, req_id=rid,
                           to=leader, issued=t)
                self.outstanding[ci] = rec
                self._maybe_dup(t, rec)
        # reads: the linearizable path self-records ok/fail via the
        # history hook in ReplicatedKVS.get
        if leader >= 0 and self.rng.random() < self.p_read:
            self.kv.get(leader, self.rng.choice(self.keys),
                        linearizable=True)
        if self.rng.random() < self.p_weak:
            live = [r for r in range(self.kv.c.R) if r not in down]
            if live:
                self.kv.get(self.rng.choice(live),
                            self.rng.choice(self.keys))
        self._issue_reads(t, leader, down)

    def _issue_reads(self, t: int, leader: int, down) -> None:
        """The read-scaling mix (when the runner attached the read
        path): a linearizable read AT THE LEASE HOLDER — even a
        freshly deposed one, so chaos proves an expired/revoked lease
        refuses rather than serves stale — and a READ-INDEX read
        queued at a random live replica, drained by the hub at the
        linearization point. All linearizable: the Wing–Gong checker
        verdicts every one of them."""
        hub = getattr(self.kv.c, "reads", None)
        if hub is None:
            return
        rr = self.rng_reads
        lm = self.kv.c.leases
        if rr.random() < self.p_holder_read:
            holder = (lm.serving_holder(0) if lm is not None else -1)
            target = holder if holder >= 0 else leader
            if target >= 0 and target not in down:
                # a crashed process serves nothing; a PARTITIONED
                # holder is the interesting case and stays eligible
                self.kv.get(target, rr.choice(self.keys),
                            linearizable=True)
        if rr.random() < self.p_follower_read:
            live = [r for r in range(self.kv.c.R) if r not in down]
            if live:
                f = rr.choice(live)
                key = rr.choice(self.keys)
                op_id = self.h.invoke("get", key, replica=f)

                def done(status, value, _op=op_id):
                    if status == "ok":
                        self.h.ok(_op, value)
                    else:
                        # never served: definitively did not happen
                        self.h.fail(_op, reason="read_unserved")

                hub.submit(
                    lambda f=f, k=key: self.kv.serve_local(f, k),
                    replica=f, patience=self.read_patience,
                    step0=t, on_done=done)

    def finish(self) -> None:
        """Run end: every still-unresolved op is ambiguous."""
        for out in self.outstanding:
            if out is not None:
                self.h.timeout(out["op_id"])
        for op_id in self.h.pending():
            self.h.timeout(op_id)


class NemesisRunner:
    """One seeded chaos run over a fresh in-process cluster."""

    def __init__(self, cfg: Optional[LogConfig] = None,
                 n_replicas: int = 3, *, seed: int = 0,
                 steps: int = 120, schedule: Optional[FaultSchedule]
                 = None, fault_kinds=("partition", "crash", "drop",
                                      "delay", "dup", "skew"),
                 n_clients: int = 2, n_keys: int = 3,
                 workload_opts: Optional[dict] = None,
                 fanout: str = "gather", kvs_cap: int = 256,
                 settle_steps: int = 30,
                 artifact_path: Optional[str] = None,
                 skip_incompatible_faults: bool = False,
                 obs: Optional[Observability] = None,
                 audit: bool = True, pipeline: int = 0,
                 scan: bool = False,
                 governor: bool = False,
                 leases: bool = True,
                 repair: bool = False,
                 corrupt_step: Optional[int] = None,
                 corrupt_offset: int = 1,
                 repair_opts: Optional[dict] = None,
                 streams: bool = False,
                 cdc_path: Optional[str] = None):
        self.cfg = cfg or DEFAULT_KV_CFG
        self.R = int(n_replicas)
        self.seed = int(seed)
        self.steps = int(steps)
        self.settle_steps = int(settle_steps)
        self.artifact_path = artifact_path
        self.workload_opts = dict(workload_opts or {})
        self.obs = obs if obs is not None else Observability()
        # chaos runs are short and their whole point is post-mortem
        # evidence: trace EVERY command so a violation artifact ships
        # the complete causal timeline — but only on a runner-OWNED
        # facade; a caller-supplied (possibly shared, possibly live-
        # production) facade keeps its configured sampling rate
        if obs is None:
            self.obs.spans.set_sample_every(1)
        if schedule is None:
            schedule = generate_schedule(seed, self.R, steps,
                                         kinds=fault_kinds)
        schedule.validate(self.R)
        # fanout guard — up front, never mid-run (see module docstring)
        if fanout == "psum" and schedule.mask_affecting():
            if not skip_incompatible_faults:
                raise ValueError(
                    "fanout='psum' cannot model partitions/crashes/"
                    "link faults (single-contributor broadcast needs "
                    "full connectivity); build with fanout='gather' "
                    "or pass skip_incompatible_faults=True")
            n_dropped = len(schedule.mask_affecting())
            schedule = schedule.without_mask_faults()
            log.warning(
                "chaos: fanout='psum' — skipping %d mask-affecting "
                "fault(s) (partition/crash/drop/delay need 'gather')",
                n_dropped)
        self.schedule = schedule
        # chaos runs audit at 100% by default: every committed entry is
        # digest-checked across replicas every step, so a run that
        # passes also PROVES bit-identical replicated state under the
        # schedule (and a divergence ships audit + flight evidence in
        # the reproducer artifact)
        self.cluster = SimCluster(self.cfg, self.R, fanout=fanout,
                                  audit=audit)
        self.cluster.obs = self.obs
        # self-healing mode (runtime/repair.py): a scripted bit
        # corruption at ``corrupt_step`` (victim = leader +
        # ``corrupt_offset``, target = the min committed index — both
        # derived from protocol state, so same-seed runs corrupt the
        # same slot) is detected by the audit, quarantined, repaired
        # from a ledger-majority donor, backfilled, and re-admitted —
        # and the verdict requires the loop to have CLOSED (zero
        # unrepaired findings, no replica still held). The repair
        # timeline (step-domain, deterministic) rides the verdict and
        # any reproducer artifact.
        self.repairer = None
        if repair:
            if not audit:
                raise ValueError("repair=True requires audit=True")
            from rdma_paxos_tpu.runtime.repair import RepairController
            self.repairer = RepairController(self.cluster,
                                             obs=self.obs,
                                             **(repair_opts or {}))
        self.corrupt_step = corrupt_step
        self.corrupt_offset = int(corrupt_offset)
        self.corrupted: Optional[tuple] = None   # (victim, index)
        # read path (runtime/reads.py): chaos runs exercise leader
        # leases + read-index follower reads BY DEFAULT — every
        # linearizable read lands in the checked history, so a lease
        # serving stale state under the schedule is a caught
        # violation, and the lease timeline (grant/renew/expire/
        # revoke) rides the trace ring into any reproducer artifact
        if leases:
            from rdma_paxos_tpu.runtime import reads as reads_mod
            reads_mod.attach(self.cluster)
        self.link = LinkModel(self.R, seed=seed)
        self.link.obs = self.obs
        self.cluster.link_model = self.link
        self.kv = ReplicatedKVS(self.cluster, cap=kvs_cap)
        # streams=True: an all-keys watch subscription rides the whole
        # run and the verdict proves EXACTLY-ONCE delivery against an
        # independent fold of the committed stream — including across
        # two scripted close-and-resume-with-token reconnects at
        # seeded mid-run steps (leader crashes land in between under
        # any crash-bearing schedule). Its rng is separate, so pinned
        # seeds' workload/schedule sequences are unchanged. cdc_path
        # additionally exports every pumped record for
        # ``streams verify`` against the run's audit ledger.
        self.streams_hub = None
        self._watch_sub = None
        self._watch_events: List = []
        self._watch_resumes = 0
        if streams:
            from rdma_paxos_tpu import streams as streams_mod
            rng_s = random.Random(f"streams:{seed}")
            self.streams_hub = streams_mod.attach(
                self.cluster, kvs=self.kv, obs=self.obs,
                cdc_path=cdc_path, auditor=self.cluster.auditor)
            self._watch_sub = self.streams_hub.subscribe(0)
            lo, hi = max(2, steps // 4), max(3, steps // 2)
            self._watch_resume_at = {
                rng_s.randrange(lo, hi),
                rng_s.randrange(hi, max(hi + 1, (3 * steps) // 4))}
        self.history = HistoryRecorder()
        self.kv.history = self.history
        self.hard = HardStateTracker(self.R)
        self.timers = StepTimerModel(self.R, seed=seed)
        self.invariants = InvariantChecker(self.R)
        self.workload = _Workload(self.kv, self.history, seed,
                                  n_clients, n_keys,
                                  **self.workload_opts)
        self.n_clients, self.n_keys = n_clients, n_keys
        self.fanout = fanout
        # pipeline >= 2: drive the cluster the way the pipelined
        # driver does — up to that many dispatches in flight on the
        # stable-leader path (begin_step, ring-room checked), draining
        # to the serial step whenever a fault event is due, a timer
        # fires, or the leader is unknown. The chaos verdict must stay
        # green: pipelining is a pure latency transform (the pinning
        # tests in tests/test_pipeline.py assert bit-identity too).
        self.pipeline = int(pipeline)
        self._pl: List[tuple] = []  # (logical step id, ticket) in flight
        # scan=True: stable-leader traffic iterations ride the
        # device-resident K-window scan tier (cluster.step_burst with
        # the scan program — fused steps, consolidated readback,
        # in-dispatch replay rows), DRAINING TO THE SERIAL single-step
        # path the moment a fault event is due, a timer fires, or the
        # leader is unknown — so a leader crash mid-run is handled by
        # exactly the election machinery the serial drive uses. The
        # verdict must stay green: the scan tier is bit-identical to
        # serial steps (tests/test_scan.py pins it engine-level).
        self.scan = bool(scan)
        if scan:
            if pipeline >= 2:
                raise ValueError(
                    "runner scan mode and pipelined mode are "
                    "mutually exclusive (bursts are serial-path)")
            self.cluster.scan = True
        # governor=True: the adaptive dispatch governor rides the run
        # — observed on every finish (the engines' hook), consulted by
        # the fused/pipelined drives, and DRAINED TO SERIAL exactly
        # like elections and repair: any iteration with a fault event
        # due, a timer firing, or an unknown leader runs the serial
        # single step regardless of the governor's tier, and a serial
        # governor decision itself forces the serial path. Decisions
        # are pure step-domain functions of the observed backlog /
        # arrival stream, so same-seed verdicts stay bit-reproducible
        # (tests/test_governor.py pins determinism + zero violations).
        self.governor = None
        if governor:
            from rdma_paxos_tpu.runtime.governor import attach_governor
            self.governor = attach_governor(self.cluster, obs=self.obs)

    # ------------------------------------------------------------------

    def _config_doc(self) -> dict:
        return dict(
            log=dict(n_slots=self.cfg.n_slots,
                     slot_bytes=self.cfg.slot_bytes,
                     window_slots=self.cfg.window_slots,
                     batch_slots=self.cfg.batch_slots,
                     rebase_threshold=self.cfg.rebase_threshold),
            n_replicas=self.R, steps=self.steps,
            settle_steps=self.settle_steps, fanout=self.fanout,
            n_clients=self.n_clients, n_keys=self.n_keys,
            workload_opts=self.workload_opts)

    def _observe_res(self, t: int, res,
                     violations: List[dict]) -> int:
        """Post-step observation rules for one finished step's outputs
        (shared by the serial and pipelined drives)."""
        self.hard.observe(res)
        self.timers.observe(res)
        try:
            self.invariants.check_step(
                res, step=t, rebased_total=self.cluster.rebased_total)
        except InvariantViolation as v:
            violations.append(v.as_dict())
            self.obs.trace.record(obs_trace.NEMESIS_VIOLATION,
                                  **v.as_dict())
        leader = _leader_of(res)
        self.workload.observe(t, leader)
        if self._watch_sub is not None:
            self._watch_events.extend(self._watch_sub.poll(
                max_n=1 << 16))
            if t in self._watch_resume_at:
                # scripted reconnect: resume from the last CONSUMED
                # event's token — the exactly-once contract says the
                # concatenated event sequence must stay gapless and
                # duplicate-free across it
                tok = (self._watch_events[-1].token()
                       if self._watch_events else None)
                self._watch_sub.close()
                self._watch_sub = self.streams_hub.subscribe(
                    0, token=tok)
                self._watch_resumes += 1
        if self.repairer is not None:
            self.repairer.observe()
        return leader

    def _finish_one(self, violations: List[dict]) -> int:
        t, ticket = self._pl.pop(0)
        res = self.cluster.finish(ticket)
        return self._observe_res(t, res, violations)

    def _drain(self, leader: int, violations: List[dict]) -> int:
        while self._pl:
            leader = self._finish_one(violations)
        return leader

    def _pipeline_eligible(self, t: int, leader: int) -> bool:
        """The stable-leader dispatch-without-finishing window: no
        fault event due this step, a known leader, an initialized
        cluster. Ring room is checked separately (``_room_ok``) AFTER
        the workload issues this step's entries — a pre-issue check
        would not cover them."""
        if self.pipeline < 2:
            return False
        # a governor that has disengaged pipelining (or shed to
        # serial) drains the in-flight window — the same serial-path
        # discipline elections and repair use
        if (self.governor is not None
                and not self.governor.decision.pipeline):
            return False
        return self._stable_window(t, leader)

    def _corrupt_due(self, t: int) -> bool:
        return (self.corrupt_step is not None
                and self.corrupted is None
                and t >= self.corrupt_step)

    def _timer_excluded(self):
        """Replicas whose election timers must not fire: crashed ones
        and — under repair — quarantined/probation ones (an isolated
        quarantined replica's futile candidacies would only inflate
        its local term; a probation replica must not lead)."""
        if self.repairer is None:
            return self.link.down
        return self.link.down | self.repairer.blocked_replicas(0)

    def _room_ok(self) -> bool:
        """Ring room for the WHOLE pending backlog (including entries
        the workload just issued), so a shortfall requeue — which
        would reorder against in-flight dispatches — is impossible;
        elections cannot start in flight because in-flight dispatches
        carried no timeouts."""
        c = self.cluster
        reserved = c.reserved_appends()
        last = c.last
        return all(
            len(c.pending[r]) + int(reserved[r])
            <= (self.cfg.n_slots - 1) - (int(last["end"][r])
                                         - int(last["head"][r]))
            for r in range(self.R))

    def _stable_window(self, t: int, leader: int) -> bool:
        """The shared fused-dispatch eligibility predicate (pipelined
        AND scan drives): a known leader, an initialized cluster, no
        fault event due this step, no corruption pending, no repair
        needing a drained serial iteration."""
        if leader < 0:
            return False
        if self._corrupt_due(t):
            return False
        if self.repairer is not None and self.repairer.needs_drain():
            return False
        return (self.cluster.last is not None
                and not self.schedule.due(t))

    def _scan_eligible(self, t: int, leader: int) -> bool:
        """The scan tier's window: the shared stable-window rule PLUS
        no per-step-random link fault active. A K-fused dispatch
        samples the link model's effective mask ONCE for all K steps,
        so active drop/delay/dup state (whose randomness keys on the
        per-step clock) would be under-injected inside a scan — drain
        to the serial path until it clears. Static masks (crashes,
        blocks, partitions) apply identically on every fused step and
        fuse soundly."""
        if not self.scan:
            return False
        if self.link.drop or self.link.delay or self.link.dup:
            return False
        # a serial governor decision drains the scan tier too
        if (self.governor is not None
                and self.governor.decision.max_k <= 1):
            return False
        return self._stable_window(t, leader)

    def _one_step(self, t: int, leader: int,
                  violations: List[dict]) -> int:
        self.history.set_clock(t)
        if self._scan_eligible(t, leader):
            self.workload.issue(t, leader, self.link.down)
            timeouts = self.timers.fire(self._timer_excluded())
            if (not timeouts and self._room_ok()
                    and any(len(q) for q in self.cluster.pending)):
                # K-window scan dispatch (K sized to the backlog,
                # capped at the governor's rung when one is attached)
                res = self.cluster.step_burst(
                    max_k=(self.governor.decision.max_k
                           if self.governor is not None else None))
            else:
                res = self.cluster.step(timeouts=timeouts)
            return self._observe_res(t, res, violations)
        if self._pipeline_eligible(t, leader):
            self.workload.issue(t, leader, self.link.down)
            timeouts = self.timers.fire(self._timer_excluded())
            if not timeouts and self._room_ok():
                self._pl.append((t, self.cluster.begin_step()))
                if len(self._pl) >= self.pipeline:
                    leader = self._finish_one(violations)
                return leader
            # a timer fired (or the ring can no longer cover the
            # issued backlog): drain and run the serial step
            leader = self._drain(leader, violations)
            res = self.cluster.step(timeouts=timeouts)
            return self._observe_res(t, res, violations)
        # serial path: fault events mutate cluster/link state and must
        # never run under in-flight dispatches
        leader = self._drain(leader, violations)
        if self._corrupt_due(t) and leader >= 0 \
                and self.cluster.last is not None \
                and int(self.cluster.last["commit"].min()) >= 1:
            from rdma_paxos_tpu.chaos.faults import corrupt_slot
            victim = (leader + self.corrupt_offset) % self.R
            target = int(self.cluster.last["commit"].min()) - 1
            corrupt_slot(self.cluster, victim, target)
            self.corrupted = (victim, target)
        if self.repairer is not None:
            for (_g, rr) in self.repairer.drive():
                # a snapshot re-install legitimately rewrites the
                # repaired replica's offsets — same invariant-baseline
                # reset as a crash restart
                self.invariants.reset_replica(rr)
        fired = self.schedule.apply(t, self.cluster, self.link,
                                    timers=self.timers, hard=self.hard,
                                    kvs=self.kv)
        for ev in fired:
            if ev["op"] == "restart":
                self.invariants.reset_replica(ev["replica"])
        self.workload.issue(t, leader, self.link.down)
        timeouts = self.timers.fire(self._timer_excluded())
        res = self.cluster.step(timeouts=timeouts)
        return self._observe_res(t, res, violations)

    def run(self) -> Dict:
        """Execute the schedule, settle, check. Returns the verdict
        dict (deterministic for a given seed: no wall-clock fields);
        writes a reproducer artifact when anything failed."""
        violations: List[dict] = []
        leader = -1
        for t in range(self.steps):
            leader = self._one_step(t, leader, violations)
            if violations:
                break
        # drain any in-flight pipelined dispatches before host-side
        # state surgery (restarts) or the convergence sweep
        leader = self._drain(leader, violations)
        # settle: clear faults, revive the dead, let the cluster
        # converge so the convergence invariant and pending ops resolve
        self.history.set_clock(self.steps)
        self.link.heal()
        if not violations:
            from rdma_paxos_tpu.chaos.faults import restart_replica
            for r in sorted(self.link.down):
                restart_replica(self.cluster, r, self.link,
                                hard=self.hard, kvs=self.kv)
                self.invariants.reset_replica(r)
            for t in range(self.steps, self.steps + self.settle_steps):
                leader = self._one_step(t, leader, violations)
                if violations:
                    break
            leader = self._drain(leader, violations)
        if self.cluster.reads is not None:
            # still-queued reads will never be confirmed: fail them
            # (their history records close as FAIL — constraint-free)
            self.cluster.reads.fail_all("run end")
        self.workload.finish()
        if not violations:
            try:
                self.invariants.check_convergence(self.cluster.replayed)
            except InvariantViolation as v:
                violations.append(v.as_dict())
        linz = check_history(self.history.ops())
        audit_summary = (self.cluster.auditor.summary()
                         if self.cluster.auditor is not None else None)
        repair_summary = (self.repairer.status()
                          if self.repairer is not None else None)
        if self.repairer is not None:
            # self-healing acceptance: the loop must have CLOSED —
            # every divergence repaired + backfilled, no replica still
            # quarantined/on probation/escalated
            audit_ok = (audit_summary is not None
                        and audit_summary["unrepaired"] == 0
                        and not repair_summary["active"])
        else:
            audit_ok = (audit_summary is None
                        or audit_summary["findings"] == 0)
        streams_summary = (self._streams_summary()
                           if self.streams_hub is not None else None)
        streams_ok = (streams_summary is None
                      or (streams_summary["dups"] == 0
                          and streams_summary["gaps"] == 0))
        ok = (not violations and linz["ok"] is True and audit_ok
              and streams_ok)
        verdict: Dict = dict(
            ok=ok, seed=self.seed, steps=self.steps,
            schedule_events=len(self.schedule),
            invariant_violations=violations,
            linearizability=dict(ok=linz["ok"],
                                 violations=linz["violations"],
                                 undecided=linz["undecided"],
                                 ops=linz["ops"],
                                 states=linz["states"]),
            audit=audit_summary,
            repair=repair_summary,
            corrupted=self.corrupted,
            history_events=len(self.history),
            client_ops=len(self.history.ops(include_weak=True)),
        )
        if self.cluster.reads is not None:
            # deterministic read-path summary: per-path served totals
            # (registry accounting), hub state, lease timeline counts
            from rdma_paxos_tpu.runtime.reads import read_counts
            verdict["reads"] = dict(
                read_counts(self.obs),
                hub=self.cluster.reads.status(),
                leases=self.cluster.leases.status())
        if self.governor is not None:
            # pure step-domain controller state: same seed -> same
            # tier sequence -> identical summary (determinism pinned)
            verdict["governor"] = self.governor.status()
        if streams_summary is not None:
            verdict["streams"] = streams_summary
        if not ok:
            # ok=None (state budget exceeded) is NOT a found violation —
            # label it honestly so nobody chases a bug that was never
            # detected; the artifact still ships for a deeper re-check
            reason = ("invariant violation" if violations
                      else "linearizability violation"
                      if linz["violations"]
                      else "audit divergence" if not audit_ok
                      else "watch delivery violated exactly-once"
                      if not streams_ok
                      else "linearizability undecided "
                           "(checker state budget exceeded)")
            verdict["artifact"] = chaos_artifact.write_reproducer(
                self.artifact_path, seed=self.seed,
                schedule=self.schedule, reason=reason,
                config=self._config_doc(),
                history=self.history.to_jsonl(),
                violation=dict(invariants=violations,
                               linearizability={
                                   "violations": linz["violations"],
                                   "undecided": linz["undecided"]},
                               audit=audit_summary),
                obs=self.obs, extra={
                    "verdict": {k: v for k, v in verdict.items()
                                if k != "artifact"},
                    # the audit ledger dump + flight-recorder ring ride
                    # every reproducer so a divergence is localizable
                    # (and the seeded run replayable) from the artifact
                    "audit": (self.cluster.auditor.dump()
                              if self.cluster.auditor is not None
                              else None),
                    "repair": repair_summary,
                    "flight": (self.cluster.flight.dump()
                               if self.cluster.flight is not None
                               else None)})
        elif (self.artifact_path and repair_summary is not None
                and repair_summary["timeline"]):
            # a HEALED run still ships its evidence when asked: the
            # deterministic repair timeline + ledger (with the repair
            # records closing the findings) — the self-healing loop's
            # post-incident document
            verdict["artifact"] = chaos_artifact.write_reproducer(
                self.artifact_path, seed=self.seed,
                schedule=self.schedule,
                reason="divergence repaired (self-healed)",
                config=self._config_doc(),
                history=self.history.to_jsonl(),
                violation=dict(invariants=[], linearizability={},
                               audit=audit_summary),
                obs=self.obs, extra={
                    "verdict": {k: v for k, v in verdict.items()
                                if k != "artifact"},
                    "audit": self.cluster.auditor.dump(),
                    "repair": repair_summary,
                    "flight": (self.cluster.flight.dump()
                               if self.cluster.flight is not None
                               else None)})
        return verdict

    def _streams_summary(self) -> Dict:
        """Flush the watch pump to the final committed frontier, drain
        the subscription, and verdict exactly-once delivery against an
        INDEPENDENT fold of the committed stream. Identity is the
        ``(conn, req)`` pair — the dedup registry's own key, stable
        whether or not log coordinates survived restarts — so the
        check is: zero duplicates, zero gaps, and in committed order,
        across every scripted token resume. Deterministic for a seed:
        the committed stream and the event set are; only the
        resume split points move within it."""
        from rdma_paxos_tpu.streams.tail import (
            DedupFold, OP_PUT, OP_RM, decode_kvs)
        hub = self.streams_hub
        tail = hub.tails[0]
        hub.watch.wait_caught_up({0: tail.length()})
        self._watch_events.extend(self._watch_sub.poll(max_n=1 << 20))
        fold = DedupFold()
        expect = []
        for rec in tail.records(0):
            if not fold.accept(rec):
                continue
            cmd = decode_kvs(rec.payload)
            if cmd is not None and cmd[0] in (OP_PUT, OP_RM):
                expect.append((rec.conn, rec.req))
        got = [(e.conn, e.req) for e in self._watch_events]
        seen = set()
        dups = 0
        for ident in got:
            if ident in seen:
                dups += 1
            seen.add(ident)
        gaps = sum(1 for ident in expect if ident not in seen)
        hub.fail_all("run end")
        return dict(events=len(got), expected=len(expect), dups=dups,
                    gaps=gaps, ordered=(got == expect),
                    resumes=self._watch_resumes,
                    cdc=(hub.cdc.exported(0) if hub.cdc is not None
                         else None))

    # ------------------------------------------------------------------

    @classmethod
    def replay(cls, path: str, **overrides) -> Dict:
        """Re-run a reproducer artifact: same seed, same schedule, same
        config — the deterministic harness reproduces the same history
        and verdict (the whole point of the artifact)."""
        doc = chaos_artifact.load_reproducer(path)
        cfg_doc = doc["config"]
        kw = dict(
            cfg=LogConfig(**cfg_doc["log"]),
            n_replicas=cfg_doc["n_replicas"],
            seed=doc["seed"], steps=cfg_doc["steps"],
            settle_steps=cfg_doc.get("settle_steps", 30),
            schedule=FaultSchedule(doc["schedule"]),
            fanout=cfg_doc.get("fanout", "gather"),
            n_clients=cfg_doc.get("n_clients", 2),
            n_keys=cfg_doc.get("n_keys", 3),
            workload_opts=cfg_doc.get("workload_opts") or {},
        )
        kw.update(overrides)
        return cls(**kw).run()
