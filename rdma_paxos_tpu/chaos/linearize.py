"""Per-key-partitioned Wing–Gong linearizability checker (memoized).

Checks a recorded client history (``chaos.history``) against the KVS
register model: per key, the sequence of PUT/RM/GET operations must
admit a total order that (a) respects real time — an op that completed
before another was invoked precedes it — and (b) is legal for a single
register: every GET returns the latest preceding PUT's value (or
absent after RM / initially).

Linearizability is *compositional* (Herlihy & Wing): a history is
linearizable iff each per-key subhistory is, so the search partitions
by key first — turning one exponential problem into many tiny ones.
Within a key the search is the Wing–Gong/Lowe algorithm with the
porcupine-style memoization: DFS over "which ops are already
linearized" with a visited-set keyed on ``(done-mask, register
value)`` — two search paths reaching the same mask and value have
identical futures, so the second is pruned.

Ambiguous ops (client timed out — fate unknown) may be linearized at
any point after their invocation OR may never have taken effect; the
search branches both ways (reads with unknown results constrain
nothing and are dropped up front). A search that exceeds the state
budget returns ``undecided`` rather than lying either way.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

OK, TIMEOUT = "ok", "timeout"
_ABSENT = None          # register value for "key not present"
_INF = float("inf")


class KeyResult(dict):
    """Per-key verdict: ``ok`` True/False/None (None = undecided),
    plus diagnostics (ops, states explored, and on failure the longest
    linearizable prefix as a witness)."""


def _prepare(ops: List[dict]) -> List[dict]:
    """Filter a key's ops to the checkable set: completed writes/reads
    plus ambiguous writes. Failed ops never took effect (the harness
    only records ``fail`` for definite no-ops, e.g. refused reads);
    ambiguous reads returned nothing to anyone, so they constrain
    nothing."""
    out = []
    for rec in ops:
        if rec["status"] == OK:
            out.append(rec)
        elif rec["status"] == TIMEOUT and rec["op"] in ("put", "rm"):
            out.append(rec)
    return out


def check_key(ops: List[dict], *,
              max_states: int = 500_000) -> KeyResult:
    """Check one key's subhistory (records in ``history.ops()`` form:
    ``op`` in {"put","rm","get"}, ``value`` the written value, ``out``
    the read result, ``inv``/``res`` logical times, ``res`` None for
    ambiguous)."""
    ops = _prepare(ops)
    n = len(ops)
    if n == 0:
        return KeyResult(ok=True, ops=0, states=0)
    # no hard length cap: the done-mask is an arbitrary-precision int
    # and closed-loop clients yield near-sequential histories whose
    # memoized frontier stays tiny; ``max_states`` is the honest budget
    # (exceeding it reports undecided, never a false verdict)
    inv = [rec["inv"] for rec in ops]
    res = [(_INF if rec["res"] is None else rec["res"]) for rec in ops]
    ambiguous = [rec["res"] is None for rec in ops]

    def apply(state, i) -> Tuple[bool, Optional[str]]:
        rec = ops[i]
        if rec["op"] == "put":
            return True, rec["value"]
        if rec["op"] == "rm":
            return True, _ABSENT
        return rec["out"] == state, state        # get

    full = (1 << n) - 1
    seen = set()
    states = 0
    # DFS stack: (done_mask, state, chosen list for witness)
    stack: List[Tuple[int, Optional[str], Tuple[int, ...]]] = [
        (0, _ABSENT, ())]
    best: Tuple[int, ...] = ()
    while stack:
        done, state, path = stack.pop()
        if (done, state) in seen:
            continue
        seen.add((done, state))
        states += 1
        if states > max_states:
            return KeyResult(ok=None, ops=n, states=states,
                             reason="state budget exceeded")
        if done == full:
            return KeyResult(ok=True, ops=n, states=states)
        if len(path) > len(best):
            best = path
        # real-time frontier: op i may linearize next iff no
        # unlinearized op finished before i was invoked
        min_res = min(res[j] for j in range(n) if not done >> j & 1)
        for i in range(n):
            if done >> i & 1 or inv[i] > min_res:
                continue
            legal, nstate = apply(state, i)
            if legal:
                stack.append((done | 1 << i, nstate, path + (i,)))
            if ambiguous[i]:
                # fate unknown: the op may never have executed —
                # discharge it without applying
                stack.append((done | 1 << i, state, path))
    witness = [dict(op=ops[i]["op"], value=ops[i]["value"],
                    out=ops[i]["out"], inv=ops[i]["inv"],
                    res=ops[i]["res"], op_id=ops[i].get("op_id"))
               for i in best]
    return KeyResult(ok=False, ops=n, states=states,
                     linearizable_prefix=witness,
                     unresolved=[ops[i].get("op_id") for i in range(n)
                                 if not (len(best) and i in best)])


def check_history(ops: List[dict], *,
                  max_states: int = 500_000) -> dict:
    """Partition ``ops`` by key and check each subhistory. Returns
    ``{"ok": bool|None, "keys": {key: KeyResult}, "violations":
    [key...], "undecided": [key...]}`` — ``ok`` is True only when
    every key checked clean and none were undecided."""
    by_key: Dict[str, List[dict]] = {}
    for rec in ops:
        by_key.setdefault(rec["key"], []).append(rec)
    keys = {}
    violations, undecided = [], []
    for key in sorted(by_key):
        kr = check_key(by_key[key], max_states=max_states)
        keys[key] = kr
        if kr["ok"] is False:
            violations.append(key)
        elif kr["ok"] is None:
            undecided.append(key)
    ok: Optional[bool] = not violations and not undecided
    if undecided and not violations:
        ok = None
    return dict(ok=ok, keys=keys, violations=violations,
                undecided=undecided,
                ops=sum(kr["ops"] for kr in keys.values()),
                states=sum(kr["states"] for kr in keys.values()))
