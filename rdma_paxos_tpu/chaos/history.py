"""Client-operation history recorder — the Jepsen-history analog.

``ReplicatedKVS`` promises a client-visible contract (linearizable
PUT/RM/read-index GET, dedup across failover); nothing before this
module ever RECORDED what clients observed, so nothing could check the
contract. This recorder captures the full operation history as typed
events over LOGICAL step time (set by the nemesis runner per step — no
wall clocks, so the same seed yields a byte-identical history):

* ``invoke`` — a client issued an op (PUT/RM get a ``(client,
  req_id)`` stamp; reads carry the serving replica and a ``weak``
  flag);
* ``ok`` — the op completed with a result (write observed committed,
  read returned);
* ``fail`` — the op definitively did NOT take effect (e.g. a
  linearizable read refused because leadership was unverified);
* ``timeout`` — fate unknown: the checker must treat the op as
  AMBIGUOUS (it may or may not have taken effect, at any point after
  its invocation);
* ``retransmit`` — the client (or the network duplicating its
  message) re-sent an already-stamped request; recorded so a
  reproducer shows exactly which duplicates were in flight.

Values are arbitrary bytes; JSONL serialization uses latin-1 (a
lossless byte↔str bijection), so dumps round-trip exactly.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

INVOKE, OK, FAIL, TIMEOUT, RETRANSMIT = (
    "invoke", "ok", "fail", "timeout", "retransmit")


def _enc(b: Optional[bytes]) -> Optional[str]:
    return None if b is None else b.decode("latin-1")


def _dec(s: Optional[str]) -> Optional[bytes]:
    return None if s is None else s.encode("latin-1")


class HistoryRecorder:
    """Append-only event list + per-op aggregation for the checker."""

    def __init__(self):
        self.events: List[dict] = []
        self._clock = 0
        # op_id -> mutable op record (the checker's unit)
        self._ops: Dict[int, dict] = {}
        # (client_id, req_id) -> op_id for write-completion matching
        self._by_req: Dict[tuple, int] = {}

    # ---------------- clock (logical, runner-driven) ----------------

    def set_clock(self, step: int) -> None:
        self._clock = int(step)

    # ---------------- recording ----------------

    def invoke(self, op: str, key: bytes, value: Optional[bytes] = None,
               *, client: int = 0, req_id: int = 0,
               replica: int = -1, weak: bool = False) -> int:
        op_id = len(self._ops)
        rec = dict(op_id=op_id, op=op, key=_enc(key), value=_enc(value),
                   client=client, req_id=req_id, replica=replica,
                   weak=weak, inv=self._clock, res=None, status=None,
                   out=None)
        self._ops[op_id] = rec
        if req_id > 0 and client > 0:
            self._by_req[(client, req_id)] = op_id
        self.events.append(dict(t=self._clock, ev=INVOKE, **{
            k: rec[k] for k in ("op_id", "op", "key", "value", "client",
                                "req_id", "replica", "weak")}))
        return op_id

    def _complete(self, op_id: int, status: str,
                  out: Optional[bytes] = None, **extra) -> None:
        rec = self._ops[op_id]
        if rec["status"] is not None:
            return                      # first completion wins
        rec["status"] = status
        rec["res"] = self._clock
        rec["out"] = _enc(out)
        self.events.append(dict(t=self._clock, ev=status, op_id=op_id,
                                out=_enc(out), **extra))

    def ok(self, op_id: int, out: Optional[bytes] = None) -> None:
        self._complete(op_id, OK, out)

    def fail(self, op_id: int, reason: str = "") -> None:
        self._complete(op_id, FAIL, reason=reason)

    def timeout(self, op_id: int) -> None:
        self._complete(op_id, TIMEOUT)

    def retransmit(self, op_id: int, replica: int = -1,
                   network_dup: bool = False) -> None:
        self.events.append(dict(t=self._clock, ev=RETRANSMIT,
                                op_id=op_id, replica=replica,
                                network_dup=network_dup))

    # ---------------- queries ----------------

    def op_id_for(self, client: int, req_id: int) -> Optional[int]:
        return self._by_req.get((client, req_id))

    def op(self, op_id: int) -> dict:
        return self._ops[op_id]

    def pending(self) -> List[int]:
        """Op ids with no completion event yet (at run end the runner
        times them out — fate unknown)."""
        return [i for i, rec in sorted(self._ops.items())
                if rec["status"] is None]

    def ops(self, *, include_weak: bool = False) -> List[dict]:
        """Completed-or-ambiguous op records for the linearizability
        checker, in op_id order: each has ``op/key/value/out/inv/res/
        status``; ``res is None`` (timeout) means ambiguous. Weak reads
        are excluded by default — they are recorded evidence, not part
        of the linearizable contract.

        TIMEOUT ops are exported with ``res=None`` — the checker's
        ambiguity key — even though the raw event (and the internal
        record) keeps the give-up clock as evidence. The recorder used
        to leak that clock into ``res``, which silently made every
        timed-out write a DEFINITE op bounded by the moment the client
        gave up: stricter than the documented contract ("fate unknown
        — may take effect at any later point, or never"), and a false
        violation the moment a later read observed the pre-timeout
        value after the give-up time (surfaced by the long-interval
        read-index reads of the read-scaling chaos mix)."""
        out = []
        for i in sorted(self._ops):
            rec = self._ops[i]
            if rec["weak"] and not include_weak:
                continue
            rec = dict(rec)
            if rec["status"] == TIMEOUT:
                rec["res"] = None
            out.append(rec)
        return out

    def __len__(self) -> int:
        return len(self.events)

    # ---------------- serialization ----------------

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(e, sort_keys=True)
                         for e in self.events)

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.to_jsonl())
        return path

    @classmethod
    def from_jsonl(cls, text: str) -> "HistoryRecorder":
        """Rebuild a recorder (events + op records) from a dump — the
        reproducer-replay path re-checks a persisted history without
        re-running the cluster."""
        h = cls()
        for line in text.splitlines():
            if not line.strip():
                continue
            e = json.loads(line)
            h.events.append(e)
            if e["ev"] == INVOKE:
                h._ops[e["op_id"]] = dict(
                    op_id=e["op_id"], op=e["op"], key=e["key"],
                    value=e["value"], client=e["client"],
                    req_id=e["req_id"], replica=e["replica"],
                    weak=e["weak"], inv=e["t"], res=None, status=None,
                    out=None)
                if e["req_id"] > 0 and e["client"] > 0:
                    h._by_req[(e["client"], e["req_id"])] = e["op_id"]
            elif e["ev"] in (OK, FAIL, TIMEOUT):
                rec = h._ops[e["op_id"]]
                if rec["status"] is None:
                    rec["status"] = e["ev"]
                    rec["res"] = e["t"]
                    rec["out"] = e.get("out")
        return h
