"""Cross-group strict-serializability checker for the txn subsystem.

The per-key Wing–Gong checker (:mod:`chaos.linearize`) verdicts
single-key client histories; cross-group transactions add claims it
cannot see: a transaction's writes land in SEVERAL groups' logs and
must be atomic (all groups or none) and serializable (some total order
consistent with every group's commit order). This checker reads the
claims straight from the replicated evidence — the per-group committed
replay streams, where 2PC records (``txn/records.py``) are ordinary
log entries:

* **decision uniqueness** — no tid carries both COMMIT and ABORT
  records anywhere, and at most one decision per (group, tid) after
  the session dedup rule;
* **atomicity** — a COMMIT record's participant bitmask names the
  groups that must ALL carry a COMMIT for that tid; an aborted (or
  undecided) tid must have NO commit anywhere, so staged writes can
  never partially apply (the fold only applies at its group's COMMIT);
* **staging discipline** — a group's COMMIT for tid is preceded in
  that group's log by at least one PREPARE of tid (something was
  actually staged to apply);
* **serializability** — the precedence relation "A's commit precedes
  B's commit in some group's log" over committed tids must be ACYCLIC:
  a cycle means two groups applied overlapping transactions in
  opposite orders and no serial schedule explains both. Acyclicity
  yields the witness total order (a topological sort). Strictness
  (real-time order) follows because edges come from positions in the
  committed logs themselves.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Sequence, Tuple

from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.txn.records import (
    TXN_ABORT, TXN_CMD_W, TXN_COMMIT, TXN_PREPARE, decode_record)


def extract_txn_events(stream) -> List[Tuple[int, int, int, int]]:
    """Ordered ``(pos, txn_op, tid, arg)`` events of one replica's
    replay stream, (conn, req)-deduplicated exactly like the
    state-machine fold (a coordinator retransmit appears once)."""
    events = []
    seen_req: Dict[int, int] = {}
    pos = 0
    for etype, conn, req, payload in stream:
        pos += 1
        if etype != int(EntryType.SEND):
            continue
        if len(payload) != TXN_CMD_W * 4:
            continue
        if req > 0 and conn > 0:
            if req <= seen_req.get(conn, 0):
                continue
            seen_req[conn] = req
        txn_op, tid, arg, _cmd = decode_record(payload)
        events.append((pos, txn_op, tid, arg))
    return events


def check_txn_streams(streams: Sequence) -> Dict:
    """Verdict the strict-serializability claims over per-group
    committed streams (``streams[g]`` = one replica's replay stream of
    group ``g`` — any replica works, committed prefixes agree).
    Returns ``{ok, violations, committed, aborted, order}`` where
    ``order`` is the witness serial order of committed tids."""
    G = len(streams)
    violations: List[dict] = []
    per_group = [extract_txn_events(s) for s in streams]
    commits: Dict[int, Dict[int, int]] = collections.defaultdict(dict)
    prepares: Dict[int, Dict[int, int]] = collections.defaultdict(dict)
    masks: Dict[int, int] = {}
    aborted: set = set()
    for g, events in enumerate(per_group):
        for pos, txn_op, tid, arg in events:
            if txn_op == TXN_PREPARE:
                prepares[tid].setdefault(g, pos)
            elif txn_op == TXN_COMMIT:
                if g in commits[tid]:
                    violations.append(dict(
                        kind="duplicate_commit", tid=tid, group=g))
                commits[tid][g] = pos
                masks.setdefault(tid, arg)
                if arg != masks[tid]:
                    violations.append(dict(
                        kind="mask_mismatch", tid=tid, group=g))
            elif txn_op == TXN_ABORT:
                aborted.add(tid)
    for tid in sorted(commits):
        if tid in aborted:
            violations.append(dict(kind="commit_and_abort", tid=tid))
        mask = masks.get(tid, 0)
        members = {g for g in range(G) if mask & (1 << g)}
        missing = members - set(commits[tid])
        if missing:
            violations.append(dict(
                kind="partial_commit", tid=tid,
                missing_groups=sorted(missing)))
        extra = set(commits[tid]) - members
        if extra:
            violations.append(dict(
                kind="commit_outside_mask", tid=tid,
                groups=sorted(extra)))
        for g, cpos in commits[tid].items():
            ppos = prepares.get(tid, {}).get(g)
            if ppos is None or ppos >= cpos:
                violations.append(dict(
                    kind="commit_without_prepare", tid=tid, group=g))
    # precedence graph over committed tids: edge a -> b when a's
    # commit precedes b's in some group's log
    committed = sorted(commits)
    edges: Dict[int, set] = {t: set() for t in committed}
    for g, events in enumerate(per_group):
        seq = [tid for _pos, op, tid, _a in events
               if op == TXN_COMMIT and tid in edges]
        for i, a in enumerate(seq):
            for b in seq[i + 1:]:
                if a != b:
                    edges[a].add(b)
    # Kahn's algorithm: a completed topological sort IS the witness
    # serial order; leftovers form the cycle
    indeg = {t: 0 for t in committed}
    for a, outs in edges.items():
        for b in outs:
            indeg[b] += 1
    ready = sorted(t for t, d in indeg.items() if d == 0)
    order: List[int] = []
    while ready:
        t = ready.pop(0)
        order.append(t)
        for b in sorted(edges[t]):
            indeg[b] -= 1
            if indeg[b] == 0:
                ready.append(b)
        ready.sort()
    if len(order) != len(committed):
        violations.append(dict(
            kind="serialization_cycle",
            tids=sorted(set(committed) - set(order))))
    return dict(ok=not violations, violations=violations,
                committed=committed, aborted=sorted(aborted),
                order=order)
