"""Chaos subsystem: deterministic fault injection + client-visible
linearizability checking — the Jepsen-style harness the reference never
had (SURVEY.md §5: its safety argument is design-by-comment).

Five parts, all host-side, all seed-deterministic:

* :mod:`~rdma_paxos_tpu.chaos.faults` — a seeded fault-schedule DSL
  (nemesis) plus the pluggable per-link model ``SimCluster`` consults
  each step: asymmetric link breaks, message drop/delay/duplication,
  crash-restart with volatile-state wipe + snapshot/StableStore-style
  recovery, and election-timeout jitter.
* :mod:`~rdma_paxos_tpu.chaos.history` — a client-operation history
  recorder (invoke/ok/fail/timeout events over logical step time,
  JSONL dump) hooked into ``ReplicatedKVS``/``ClientSession``,
  including weak reads and retransmits.
* :mod:`~rdma_paxos_tpu.chaos.linearize` — a per-key-partitioned
  Wing–Gong linearizability checker with memoization (porcupine-style)
  over the KVS register model; timed-out ops are ambiguous (may or may
  not have taken effect).
* :mod:`~rdma_paxos_tpu.chaos.invariants` — the I1–I5 protocol safety
  invariants, extracted from ``tests/test_fuzz.py`` into a reusable
  checker both the fuzzer and the nemesis runner share.
* :mod:`~rdma_paxos_tpu.chaos.runner` — the nemesis runner composing
  workload generator + fault schedule + invariants + the checker; any
  violation dumps a self-contained reproducer artifact (seed, schedule
  JSON, history JSONL, obs trace ring, metrics snapshot) via
  :mod:`~rdma_paxos_tpu.chaos.artifact`.

HARD RULE (same as :mod:`rdma_paxos_tpu.obs`): nothing here may run
inside jitted/``shard_map``ped code. The link model only rewrites the
``peer_mask`` INPUT ARRAY the step already takes — compiled-step cache
keys are bit-identical with chaos on or off (``tests/test_chaos.py``
guards it).
"""

from __future__ import annotations

from rdma_paxos_tpu.chaos.artifact import load_reproducer, write_reproducer
from rdma_paxos_tpu.chaos.faults import (
    FaultSchedule, HardStateTracker, LinkModel, StepTimerModel,
    crash_replica, generate_schedule, restart_replica)
from rdma_paxos_tpu.chaos.history import HistoryRecorder
from rdma_paxos_tpu.chaos.invariants import (
    InvariantChecker, InvariantViolation)
from rdma_paxos_tpu.chaos.linearize import check_history, check_key

__all__ = [
    "FaultSchedule", "HardStateTracker", "HistoryRecorder",
    "InvariantChecker", "InvariantViolation", "LinkModel",
    "StepTimerModel", "check_history", "check_key", "crash_replica",
    "generate_schedule", "load_reproducer", "restart_replica",
    "write_reproducer",
]
