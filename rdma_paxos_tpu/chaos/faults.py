"""Seeded fault-schedule DSL (nemesis) + the pluggable per-link model.

The pre-chaos harness expressed failures through one binary, symmetric
``peer_mask`` matrix mutated by ``SimCluster.partition()/heal()``. That
models clean partitions and nothing else. This module generalizes it
into a **per-link fault model** the cluster consults each step:

* asymmetric link breaks — ``i`` cannot hear ``j`` while ``j`` still
  hears ``i`` (the one-directional NIC/switch failures the reference's
  QP-level fencing worries about);
* probabilistic message drop per link (seeded, replayable);
* message delay — a link with a d-step delay delivers every (d+1)-th
  step. In a lock-step protocol where every step retransmits the
  current window/control state, a delivery delayed d steps is
  indistinguishable from hearing nothing for d steps and then hearing
  the CURRENT state, so the periodic gate is the exact semantics, not
  an approximation;
* message duplication — a stale extra delivery forced through an
  otherwise dropped/delayed step. Window absorption is idempotent and
  term-gated, so duplicates must be harmless; modeling them lets the
  invariant checker PROVE that instead of assuming it;
* crash-restart — a crashed replica is silent (hears nobody, heard by
  nobody); restart wipes its volatile device state and recovers from
  "stable storage": its own applied prefix (the StableStore analog —
  ``SimCluster.replayed`` is exactly what the driver persists) plus
  the HardState/peer-vote-record election durability, via the same
  ``take_snapshot``/``install_snapshot``/``recover_vote`` path the
  real driver uses;
* election-timeout jitter/skew — a deterministic step-domain timer
  model (:class:`StepTimerModel`) whose per-replica periods are seeded
  and can be skewed mid-schedule by the nemesis.

Everything is host-side. The link model only rewrites the ``peer_mask``
INPUT ARRAY of the already-compiled step — it can never change a
compiled-step cache key (guarded by ``tests/test_chaos.py``). The
effective mask is a PURE function of (model state, step index): the
per-step randomness is derived from ``(seed, step_index)`` rather than
a shared mutable RNG, so replaying a schedule from an artifact yields
bit-identical masks regardless of call count or ordering.
"""

from __future__ import annotations

import json
import random
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from rdma_paxos_tpu.obs import trace as obs_trace

# ---------------------------------------------------------------------------
# per-link model
# ---------------------------------------------------------------------------

# link keys are (dst, src): "dst cannot hear src" — matching the
# peer_mask[receiver, sender] orientation of StepInput.peer_mask


def _links(n: int, dst, src) -> List[Tuple[int, int]]:
    """Expand (dst, src) with None wildcards into concrete link pairs
    (diagonal excluded — a replica always hears itself)."""
    dsts = range(n) if dst is None else [int(dst)]
    srcs = range(n) if src is None else [int(src)]
    return [(d, s) for d in dsts for s in srcs if d != s]


class LinkModel:
    """Pluggable per-link fault state; attach via ``cluster.link_model``.

    ``effective_mask(base, step_idx)`` composes, in precedence order
    (later wins): base mask → delay gating → probabilistic drop →
    forced duplicate delivery → asymmetric blocks → crashed replicas →
    diagonal always on. Duplication deliberately overrides drop/delay
    (a stale copy squeaking through) but never blocks or crashes.
    """

    def __init__(self, n_replicas: int, seed: int = 0):
        self.R = int(n_replicas)
        self.seed = int(seed)
        self.down: Set[int] = set()
        self.blocked: Set[Tuple[int, int]] = set()
        self.drop: Dict[Tuple[int, int], float] = {}
        self.delay: Dict[Tuple[int, int], int] = {}
        self.dup: Dict[Tuple[int, int], float] = {}
        self.faults_active = 0          # bookkeeping for health/verdicts
        self.obs = None                 # optional Observability facade

    # ---------------- mutation (nemesis-facing) ----------------

    def _record(self, kind: str, **fields) -> None:
        self.faults_active = (len(self.down) + len(self.blocked)
                              + len(self.drop) + len(self.delay)
                              + len(self.dup))
        if self.obs is not None:
            self.obs.metrics.inc("faults_injected_total")
            self.obs.trace.record(obs_trace.FAULT_INJECTED, fault=kind,
                                  **fields)

    def block(self, dst: Optional[int], src: Optional[int]) -> None:
        """``dst`` stops hearing ``src`` (None = wildcard). Asymmetric:
        the reverse direction is untouched."""
        self.blocked.update(_links(self.R, dst, src))
        self._record("block", dst=dst, src=src)

    def unblock(self, dst: Optional[int] = None,
                src: Optional[int] = None) -> None:
        self.blocked.difference_update(_links(self.R, dst, src))
        self._record("unblock", dst=dst, src=src)

    def partition(self, groups: Sequence[Sequence[int]]) -> None:
        """Symmetric split expressed as blocks (unlike
        ``SimCluster.partition()`` this composes with other faults and
        heals without clobbering them). Replicas NOT listed in any
        group are fully isolated — each forms an implicit singleton
        group — matching ``SimCluster.partition()``'s semantics exactly
        so a schedule means the same fault under either API."""
        member = {}
        for gi, g in enumerate(groups):
            for r in g:
                member[int(r)] = gi
        for i in range(self.R):
            member.setdefault(i, -1 - i)     # unlisted: isolated
        for i in range(self.R):
            for j in range(self.R):
                if i != j and member[i] != member[j]:
                    self.blocked.add((i, j))
        self._record("partition", groups=[list(map(int, g))
                                          for g in groups])

    def set_drop(self, p: float, dst: Optional[int] = None,
                 src: Optional[int] = None) -> None:
        for link in _links(self.R, dst, src):
            if p > 0:
                self.drop[link] = float(p)
            else:
                self.drop.pop(link, None)
        self._record("drop", p=p, dst=dst, src=src)

    def set_delay(self, d: int, dst: Optional[int] = None,
                  src: Optional[int] = None) -> None:
        for link in _links(self.R, dst, src):
            if d > 0:
                self.delay[link] = int(d)
            else:
                self.delay.pop(link, None)
        self._record("delay", d=d, dst=dst, src=src)

    def set_dup(self, p: float, dst: Optional[int] = None,
                src: Optional[int] = None) -> None:
        for link in _links(self.R, dst, src):
            if p > 0:
                self.dup[link] = float(p)
            else:
                self.dup.pop(link, None)
        self._record("dup", p=p, dst=dst, src=src)

    def heal(self) -> None:
        """Clear every link fault (crashed replicas stay down — only
        ``restart_replica`` brings one back)."""
        self.blocked.clear()
        self.drop.clear()
        self.delay.clear()
        self.dup.clear()
        self._record("heal")

    # ---------------- the per-step mask ----------------

    def faulty(self) -> bool:
        """Any state that could yield a non-full mask (the psum
        compatibility question — see NemesisRunner's fanout guard)."""
        return bool(self.down or self.blocked or self.drop or self.delay)

    def effective_mask(self, base: np.ndarray,
                       step_idx: int) -> np.ndarray:
        mask = np.asarray(base, np.int32).copy()
        if not (self.down or self.blocked or self.drop or self.delay
                or self.dup):
            return mask
        for (d, s), dd in self.delay.items():
            if step_idx % (dd + 1) != dd:
                mask[d, s] = 0
        if self.drop:
            u = np.random.default_rng(
                (self.seed & 0x7FFFFFFF, step_idx)).random(
                    (self.R, self.R))
            for (d, s), p in self.drop.items():
                if u[d, s] < p:
                    mask[d, s] = 0
        if self.dup:
            u = np.random.default_rng(
                ((self.seed + 1) & 0x7FFFFFFF, step_idx)).random(
                    (self.R, self.R))
            for (d, s), p in self.dup.items():
                if u[d, s] < p:
                    mask[d, s] = 1          # stale duplicate delivery
        for d, s in self.blocked:
            mask[d, s] = 0
        for r in self.down:
            mask[r, :] = 0
            mask[:, r] = 0
        np.fill_diagonal(mask, 1)
        return mask


# ---------------------------------------------------------------------------
# crash-restart (volatile-state wipe + stable-storage recovery)
# ---------------------------------------------------------------------------

class HardStateTracker:
    """The driver persists ``(term, voted_term, voted_for)`` to a
    HardState file every step (``_ReplicaRuntime.hard``); in pure
    simulation this tracker is that file — fed from each step's outputs
    so a restart restores exactly what a real crash would have kept."""

    def __init__(self, n_replicas: int):
        self._hs = [(0, 0, -1)] * n_replicas

    def observe(self, res) -> None:
        for r in range(len(self._hs)):
            self._hs[r] = (int(res["term"][r]), int(res["voted_term"][r]),
                           int(res["voted_for"][r]))

    def get(self, r: int) -> Tuple[int, int, int]:
        return self._hs[r]


def corrupt_slot(cluster, r: int, g_idx: int, *,
                 group: Optional[int] = None, word: int = 0) -> None:
    """Flip one payload bit of the slot holding global index ``g_idx``
    in replica ``r``'s device log memory — the SILENT fault the audit
    subsystem detects and the repair pipeline (``runtime/repair.py``)
    heals. ``group`` targets one consensus group of a sharded
    cluster. Pure state surgery (no link/timer effects); callers must
    be on the drained serial path."""
    import dataclasses as _dc

    from rdma_paxos_tpu.consensus.log import Log as _Log

    slot = int(g_idx) & (cluster.cfg.n_slots - 1)
    buf = cluster.state.log.buf
    if group is None:
        buf = buf.at[int(r), slot, int(word)].add(1)
    else:
        buf = buf.at[int(group), int(r), slot, int(word)].add(1)
    cluster.state = _dc.replace(cluster.state, log=_Log(buf=buf))


def crash_replica(cluster, r: int, link: LinkModel) -> None:
    """Crash replica ``r``: it goes silent (the link model drops every
    message to and from it) until :func:`restart_replica`. Its device
    row keeps stepping in lock-step — isolated, it can neither commit
    nor vote usefully — and whatever it held in volatile memory is
    discarded at restart, which is where the crash semantics bite."""
    link.down.add(int(r))
    link._record("crash", replica=int(r))


def restart_replica(cluster, r: int, link: LinkModel,
                    hard: Optional[HardStateTracker] = None,
                    kvs=None) -> None:
    """Restart a crashed replica with a volatile-state wipe.

    Stable storage in the sim is the applied prefix (``replayed[r]`` is
    byte-for-byte what the driver's StableStore persists) plus the
    HardState triple. Recovery mirrors ``ClusterDriver._do_recover``:

    * normally the replica re-installs from its OWN stable prefix — a
      self-snapshot at ``applied[r]`` (the uncommitted/unapplied device
      suffix is lost, exactly what a crash loses);
    * a replica flagged ``need_recovery`` (its ring recycled slots past
      its apply cursor) cannot trust its own log — it recovers from a
      live donor, transferring the donor's store, like the driver's
      straggler path;
    * election durability: the restored vote is the newest of the
      HardState triple and live peers' vote records
      (``recover_vote``), so a recovered replica can never re-grant a
      vote that was already counted.
    """
    from rdma_paxos_tpu.consensus.snapshot import (
        install_snapshot, recover_vote, take_snapshot)

    r = int(r)
    donor = r
    if r in cluster.need_recovery:
        live = [p for p in range(cluster.R)
                if p != r and p not in link.down
                and p not in cluster.need_recovery]
        if not live:
            raise RuntimeError(
                "replica %d needs donor recovery but no live donor "
                "exists" % r)
        # the most caught-up live member (Raft election ordering uses
        # the same ranking) so the transferred store is maximal
        donor = max(live, key=lambda p: int(cluster.applied[p]))
    snap = take_snapshot(cluster.state, donor,
                         index=int(cluster.applied[donor]))
    vt, vf = recover_vote(cluster.state, r)
    cur_term = 0
    if hard is not None:
        cur_term, hvt, hvf = hard.get(r)
        if hvt > vt:
            vt, vf = hvt, hvf
    cluster.state = install_snapshot(cluster.state, r, snap,
                                     voted_term=vt, voted_for=vf,
                                     cur_term=cur_term)
    cluster.applied[r] = snap.index
    if donor != r:
        # store transfer: the donor's persisted history replaces r's
        from rdma_paxos_tpu.runtime.hostpath import stream_copy
        cluster.replayed[r] = stream_copy(cluster.replayed[donor])
        cluster.frames[r] = []
    cluster.need_recovery.discard(r)
    link.down.discard(r)
    link._record("restart", replica=r, donor=donor, index=snap.index)
    if link.obs is not None:
        link.obs.trace.record(obs_trace.CRASH_RESTART, replica=r,
                              donor=donor, index=snap.index)
    if kvs is not None:
        # the app process restarted too: rebuild its table by refolding
        # the store (deterministic — dedup registry included)
        kvs.rebuild(r)


# ---------------------------------------------------------------------------
# deterministic election timers (step domain)
# ---------------------------------------------------------------------------

class StepTimerModel:
    """Election timers over logical steps: per-replica periods drawn
    seeded from ``[lo, hi]`` (randomized-timeout desynchronization, the
    ``ElectionTimer`` analog with steps for seconds), re-jittered after
    every firing. The nemesis skews a replica's timer via
    :meth:`skew` — a skew < 1 models a trigger-happy node that fires
    spuriously, > 1 a sluggish one that cedes elections."""

    def __init__(self, n_replicas: int, seed: int = 0, lo: int = 6,
                 hi: int = 12):
        self.R = int(n_replicas)
        self.lo, self.hi = int(lo), int(hi)
        # string seeding hashes via sha512 — deterministic across
        # processes (tuple seeding would use PYTHONHASHSEED-randomized
        # hash(), breaking replay-from-artifact)
        self._rng = random.Random(f"timer:{seed}")
        self._skew = [1.0] * self.R
        self._period = [self._rng.randint(self.lo, self.hi)
                        for _ in range(self.R)]
        # staggered starts so the first election is not a stampede
        self._since = [self._rng.randint(0, self.lo)
                       for _ in range(self.R)]

    def skew(self, r: int, factor: float) -> None:
        self._skew[int(r)] = float(factor)

    def observe(self, res) -> None:
        """Advance per-replica clocks; a heartbeat (or being leader)
        beats the timer, exactly like the driver's loop."""
        from rdma_paxos_tpu.consensus.state import Role
        for r in range(self.R):
            if (int(res["hb_seen"][r])
                    or int(res["role"][r]) == int(Role.LEADER)):
                self._since[r] = 0
            else:
                self._since[r] += 1

    def fire(self, down: Set[int]) -> List[int]:
        """Replicas whose timers expired this step (never a crashed
        one); each firing re-draws that replica's period."""
        fired = []
        for r in range(self.R):
            if r in down:
                self._since[r] = 0
                continue
            if self._since[r] >= max(1, round(
                    self._period[r] * self._skew[r])):
                fired.append(r)
                self._since[r] = 0
                self._period[r] = self._rng.randint(self.lo, self.hi)
        return fired


# ---------------------------------------------------------------------------
# the schedule DSL
# ---------------------------------------------------------------------------

# op -> required kwargs (validated at construction so a schedule can
# never die mid-run on a typo)
_OPS = {
    "partition": ("groups",),
    "heal": (),
    "block": ("dst", "src"),
    "unblock": (),
    "drop": ("p",),
    "delay": ("d",),
    "dup": ("p",),
    "crash": ("replica",),
    "restart": ("replica",),
    "skew": ("replica", "factor"),
}
# ops that can yield a non-full effective mask (psum-incompatible)
MASK_OPS = frozenset(
    ("partition", "block", "drop", "delay", "crash", "restart"))


class FaultSchedule:
    """An ordered list of ``(step, op, kwargs)`` fault events —
    buildable fluently, JSON round-trippable (the reproducer artifact
    carries schedules in this form), and validated up front."""

    def __init__(self, events: Optional[List[dict]] = None):
        self.events: List[dict] = []
        for ev in events or []:
            self.at(ev["step"], ev["op"],
                    **{k: v for k, v in ev.items()
                       if k not in ("step", "op")})

    def at(self, step: int, op: str, **kw) -> "FaultSchedule":
        if op not in _OPS:
            raise ValueError(f"unknown fault op {op!r} "
                             f"(known: {sorted(_OPS)})")
        missing = [k for k in _OPS[op] if k not in kw]
        if missing:
            raise ValueError(f"fault {op!r} missing kwargs {missing}")
        self.events.append(dict(step=int(step), op=op, **kw))
        self.events.sort(key=lambda e: e["step"])
        return self

    def due(self, step: int) -> List[dict]:
        return [e for e in self.events if e["step"] == step]

    def mask_affecting(self) -> List[dict]:
        return [e for e in self.events if e["op"] in MASK_OPS]

    def without_mask_faults(self) -> "FaultSchedule":
        return FaultSchedule([e for e in self.events
                              if e["op"] not in MASK_OPS])

    def validate(self, n_replicas: int) -> None:
        """Reject structurally-broken schedules at construction: out of
        range replicas, restarts of never-crashed replicas, and crash
        sets that could take down a majority at once (losing a majority
        's volatile state can lose committed entries — the durability
        contract here, like the reference's, is replication to a
        quorum's memory, see driver.py's sync-cadence note)."""
        down: Set[int] = set()
        limit = (n_replicas - 1) // 2
        for ev in self.events:
            for k in ("replica", "dst", "src"):
                v = ev.get(k)
                if v is not None and not (0 <= int(v) < n_replicas):
                    raise ValueError(f"{ev}: {k}={v} out of range")
            if ev["op"] == "partition":
                seen = [r for g in ev["groups"] for r in g]
                if sorted(seen) != sorted(set(seen)) or any(
                        not (0 <= r < n_replicas) for r in seen):
                    raise ValueError(f"{ev}: bad partition groups")
            if ev["op"] == "crash":
                down.add(int(ev["replica"]))
                if len(down) > limit:
                    raise ValueError(
                        f"{ev}: schedule crashes {len(down)} replicas "
                        f"concurrently; at most {limit} of "
                        f"{n_replicas} may be down at once (quorum "
                        "memory is the durability contract)")
            if ev["op"] == "restart":
                if int(ev["replica"]) not in down:
                    raise ValueError(
                        f"{ev}: restart of a replica that is not down")
                down.discard(int(ev["replica"]))

    # ---------------- serialization ----------------

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.events, indent=indent)

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        return cls(json.loads(text))

    def __len__(self) -> int:
        return len(self.events)

    def apply(self, step: int, cluster, link: LinkModel,
              timers: Optional[StepTimerModel] = None,
              hard: Optional[HardStateTracker] = None,
              kvs=None) -> List[dict]:
        """Fire every event due at ``step`` against the live harness;
        returns the events fired (for logging/history)."""
        fired = self.due(step)
        for ev in fired:
            op = ev["op"]
            if op == "partition":
                link.partition(ev["groups"])
            elif op == "heal":
                link.heal()
            elif op == "block":
                link.block(ev["dst"], ev["src"])
            elif op == "unblock":
                link.unblock(ev.get("dst"), ev.get("src"))
            elif op == "drop":
                link.set_drop(ev["p"], ev.get("dst"), ev.get("src"))
            elif op == "delay":
                link.set_delay(ev["d"], ev.get("dst"), ev.get("src"))
            elif op == "dup":
                link.set_dup(ev["p"], ev.get("dst"), ev.get("src"))
            elif op == "crash":
                crash_replica(cluster, ev["replica"], link)
            elif op == "restart":
                restart_replica(cluster, ev["replica"], link,
                                hard=hard, kvs=kvs)
            elif op == "skew":
                if timers is not None:
                    timers.skew(ev["replica"], ev["factor"])
        return fired


def generate_schedule(seed: int, n_replicas: int, steps: int, *,
                      kinds: Sequence[str] = ("partition", "crash",
                                              "drop", "delay", "dup",
                                              "skew"),
                      intensity: float = 1.0) -> FaultSchedule:
    """Seeded nemesis schedule: a deterministic sequence of fault
    episodes (inject at ``t``, clear/restart at ``t + duration``),
    paced so the cluster gets recovery windows between episodes.
    ``intensity`` scales episode frequency. Same seed ⇒ same schedule,
    always."""
    rng = random.Random(f"schedule:{seed}")   # process-stable seeding
    sched = FaultSchedule()
    R = int(n_replicas)
    down_until: Dict[int, int] = {}
    max_down = (R - 1) // 2
    t = rng.randint(4, 10)
    while t < steps - 8:
        kind = rng.choice(list(kinds))
        dur = rng.randint(3, 10)
        end = min(t + dur, steps - 4)
        if kind == "partition":
            ids = list(range(R))
            rng.shuffle(ids)
            cut = rng.randrange(1, R)
            sched.at(t, "partition", groups=[ids[:cut], ids[cut:]])
            sched.at(end, "heal")
        elif kind == "crash":
            down = {r for r, u in down_until.items() if u > t}
            alive = [r for r in range(R) if r not in down]
            if len(down) < max_down and alive:
                r = rng.choice(alive)
                sched.at(t, "crash", replica=r)
                sched.at(end, "restart", replica=r)
                down_until[r] = end
        elif kind == "drop":
            sched.at(t, "drop", p=rng.uniform(0.1, 0.5))
            sched.at(end, "drop", p=0.0)
        elif kind == "delay":
            i, j = rng.sample(range(R), 2)
            sched.at(t, "delay", d=rng.randint(1, 3), dst=i, src=j)
            sched.at(end, "delay", d=0, dst=i, src=j)
        elif kind == "dup":
            sched.at(t, "dup", p=rng.uniform(0.2, 0.8))
            sched.at(end, "dup", p=0.0)
        elif kind == "skew":
            r = rng.randrange(R)
            sched.at(t, "skew", replica=r,
                     factor=rng.choice([0.3, 0.5, 2.0, 3.0]))
            sched.at(end, "skew", replica=r, factor=1.0)
        t = end + max(2, int(rng.randint(3, 12) / max(intensity, 1e-6)))
    sched.validate(R)
    return sched
