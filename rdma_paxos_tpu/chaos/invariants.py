"""The I1–I5 protocol safety invariants — one implementation, shared.

Extracted from ``tests/test_fuzz.py`` (which now calls this module) so
the nemesis runner, the fuzzer, and any future harness check the SAME
properties and can never drift apart:

  I1 (committed-prefix agreement): all replicas agree on entries below
      their commit indices — byte-for-byte identical replay streams.
  I2 (commit monotonicity): no replica's commit index ever regresses
      *within one process incarnation* (a crash-restart legitimately
      resumes from the stable prefix; callers report restarts via
      :meth:`InvariantChecker.reset_replica`).
  I3 (durability): once ANY replica commits index k, the entries below
      k never change on any replica that subsequently commits past k.
  I4 (single leader per term): two replicas never claim leadership in
      the same term.
  I5 (offset chain): head <= apply <= commit <= end on every replica.

Violations raise :class:`InvariantViolation` carrying enough structure
(invariant id, replica, step, detail) for the caller to dump a
reproducer artifact and surface the path in its assertion message.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from rdma_paxos_tpu.consensus.state import Role


class InvariantViolation(AssertionError):
    """A protocol safety invariant failed."""

    def __init__(self, invariant: str, detail: str, *,
                 replica: Optional[int] = None,
                 step: Optional[int] = None):
        self.invariant = invariant
        self.replica = replica
        self.step = step
        self.detail = detail
        where = []
        if step is not None:
            where.append(f"step {step}")
        if replica is not None:
            where.append(f"replica {replica}")
        loc = f" ({', '.join(where)})" if where else ""
        super().__init__(f"{invariant} violated{loc}: {detail}")

    def as_dict(self) -> dict:
        return dict(invariant=self.invariant, replica=self.replica,
                    step=self.step, detail=self.detail)


class InvariantChecker:
    """Stateful per-run checker: feed every step's outputs through
    :meth:`check_step`; run :meth:`check_convergence` over the replay
    streams after the cluster settles (the I1/I3 witness is the full
    replayed prefix, so agreement is checked once streams stop
    moving — exactly as the original fuzzer did)."""

    def __init__(self, n_replicas: int):
        self.R = int(n_replicas)
        self.prev_commit = np.zeros(self.R, np.int64)
        self.seen_terms: Dict[int, int] = {}      # term -> leader (I4)
        self.steps_checked = 0

    def reset_replica(self, r: int) -> None:
        """A crash-restart wiped replica ``r``'s volatile state: its
        commit index legitimately resumes from the stable prefix, so
        re-arm I2's monotonicity baseline for the new incarnation.
        I4's term record deliberately survives — vote durability must
        hold ACROSS restarts."""
        self.prev_commit[r] = 0

    def check_step(self, res, *, step: Optional[int] = None,
                   rebased_total: int = 0) -> None:
        """I2 + I4 + I5 over one step's outputs. ``rebased_total`` is
        the cluster's cumulative rollover delta (``SimCluster
        .rebased_total``) so commit monotonicity is judged on ABSOLUTE
        indices, immune to coordinated i32 rebases."""
        step = self.steps_checked if step is None else step
        self.steps_checked += 1
        for r in range(self.R):
            commit_abs = int(res["commit"][r]) + int(rebased_total)
            if commit_abs < self.prev_commit[r]:
                raise InvariantViolation(
                    "I2", f"commit regressed {self.prev_commit[r]} -> "
                    f"{commit_abs}", replica=r, step=step)
            self.prev_commit[r] = commit_abs
        for r in range(self.R):
            if int(res["role"][r]) == int(Role.LEADER):
                t = int(res["term"][r])
                holder = self.seen_terms.setdefault(t, r)
                if holder != r:
                    raise InvariantViolation(
                        "I4", f"two leaders in term {t}: replicas "
                        f"{holder} and {r}", replica=r, step=step)
        for r in range(self.R):
            h, a = int(res["head"][r]), int(res["apply"][r])
            c, e = int(res["commit"][r]), int(res["end"][r])
            if not (h <= a <= c <= e):
                raise InvariantViolation(
                    "I5", f"offset chain broken: head={h} apply={a} "
                    f"commit={c} end={e}", replica=r, step=step)

    def check_convergence(
            self, replayed: Sequence[Sequence[tuple]]) -> None:
        """I1 + I3: every replica's replay stream is a prefix of the
        longest one (committed-prefix agreement + durability — a
        diverging or mutated prefix fails here)."""
        streams: List[list] = [list(s) for s in replayed]
        longest = max(streams, key=len)
        for r, s in enumerate(streams):
            if s != longest[:len(s)]:
                diff = next((i for i, (a, b) in
                             enumerate(zip(s, longest))
                             if a != b), min(len(s), len(longest)))
                raise InvariantViolation(
                    "I1/I3", "replay streams diverge at apply index "
                    f"{diff} (stream len {len(s)} vs longest "
                    f"{len(longest)})", replica=r)
