"""Static configuration for the TPU-native consensus core.

The reference splits configuration across three mechanisms (SURVEY.md §5):
libconfig ``nodes.local.cfg`` (timing block, reference
``src/config-comp/config-dare.c:12-54``), env vars for per-instance identity
(``server_idx``, ``group_size``, ... — ``src/proxy/proxy.c:33-59``), and
compile-time constants (``LOG_SIZE`` ``src/include/dare/dare_log.h:76``,
``MAX_SERVER_COUNT`` ``src/include/dare/dare.h:26``).

Here everything that shapes compiled programs is a frozen dataclass — JAX
programs are traced once per static config, so these play the role of the
reference's compile-time constants, while remaining per-cluster values.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


# ---------------------------------------------------------------------------
# Log geometry
# ---------------------------------------------------------------------------

# Deepest fused burst any driver dispatches: NodeDaemon's single burst
# tier is MAX_BURST_K steps; SimCluster's K_TIERS are capacity-clamped
# per dispatch so a burst can never advance ``end`` past head +
# n_slots - 1 anyway. Defined here (not in runtime/) because the
# rebase-headroom validation below must account for it.
MAX_BURST_K = 8

# Consecutive post-threshold steps with the rebase delta pinned at 0
# before the stall is surfaced (``rebase_stalled`` counter + trace
# event — ADVICE.md #3). One definition for BOTH rollover drivers
# (SimCluster and NodeDaemon) so their stall semantics cannot drift;
# large enough to filter the benign one-or-two-step lag while a
# healthy min head catches up to an n_slots multiple.
REBASE_STALL_STEPS = 25

# Layout version of the audit digest fold (``consensus/step.py:
# digest_fold`` — which columns are folded, in what order, with what
# mixer). Digests from different layouts are INCOMPARABLE, not unequal:
# the AuditLedger stamps this into every window/dump/snapshot and
# refuses cross-epoch comparison with an ``EPOCH_MISMATCH`` finding
# (never a false ``DIVERGENCE``), so the digest layout can be upgraded
# one host at a time. Bump on ANY change to the fold. Defined here (not
# in obs/ or consensus/) because both sides — the jitted producer and
# the host-side ledger/snapshot consumers — must read the same value
# without either importing the other.
DIGEST_EPOCH = 1


@dataclasses.dataclass(frozen=True)
class LogConfig:
    """Geometry of the on-device replicated log.

    The reference log is a byte-granular 64 MB circular buffer with
    variable-size entries and wrap-around splitting rules
    (``dare_log.h:76,466-558``). Byte-granular variable-size framing is
    hostile to XLA (dynamic shapes, scalar loops), so the TPU-native log is
    **slot-based**: fixed-size slots addressed by a global monotone entry
    index; slot for global index ``g`` is ``g % n_slots``. Payloads larger
    than one slot are fragmented by the proxy into multiple SEND entries —
    semantically free for APUS, because replay is a byte stream and the
    concatenation of fragments reproduces the identical bytes in log order
    (reference replay: ``src/proxy/proxy.c:408-423``).

    All four log offsets of the reference (``head/apply/commit/end``,
    ``dare_log.h:77-103``) survive as global monotone int32 entry indices.
    """

    n_slots: int = 1024          # entries in the ring (reference: 64MB buffer)
    slot_bytes: int = 512        # payload bytes per slot (proxy fragments above)
    window_slots: int = 128      # max entries moved leader->followers per step
    batch_slots: int = 64        # max entries appended by the leader per step
    # All log offsets (head/apply/commit/end, stamped M_GIDX) are i32
    # entry indices, bounding an epoch at 2^31-1 entries. When any end
    # offset crosses this threshold the runtime performs a COORDINATED
    # REBASE — every offset on every replica (and each host's apply
    # cursor) drops by the minimum head, restoring headroom with no
    # visible effect (the reference is immune via u64 byte offsets,
    # dare_log.h:77-103; we renumber instead of widening, keeping i32
    # arithmetic on the VPU). Tests shrink it to cross the boundary.
    rebase_threshold: int = 1 << 30

    def __post_init__(self) -> None:
        if self.n_slots & (self.n_slots - 1):
            raise ValueError("n_slots must be a power of two")
        if self.slot_bytes % 4:
            raise ValueError("slot_bytes must be a multiple of 4")
        if self.window_slots > self.n_slots:
            raise ValueError("window_slots must be <= n_slots")
        if self.batch_slots > self.window_slots:
            raise ValueError("batch_slots must be <= window_slots")
        if self.rebase_threshold <= self.n_slots:
            raise ValueError("rebase_threshold must exceed n_slots")
        # end may run ahead of the threshold before the rollover lands:
        # after crossing, a fused burst can advance end by up to
        # MAX_BURST_K batches in ONE dispatch (batch_slots <= n_slots
        # per step), and a low min-head can round the agreed delta to 0
        # for further steps — so the old 2*n_slots margin was
        # insufficient under bursts (ADVICE.md #5). Require headroom
        # proportional to the max burst depth; thresholds closer to the
        # ceiling than this are rejected outright (tests that shrink
        # the threshold to cross the boundary sit far below it).
        headroom = (MAX_BURST_K + 2) * self.n_slots
        if self.rebase_threshold > (1 << 31) - 1 - headroom:
            raise ValueError(
                "rebase_threshold too close to the i32 ceiling; leave "
                f">= (MAX_BURST_K+2)*n_slots = {headroom} of headroom "
                "(fused bursts can advance end by up to "
                "MAX_BURST_K*batch_slots past the threshold before the "
                "rollover lands)")

    @property
    def slot_words(self) -> int:
        return self.slot_bytes // 4


# ---------------------------------------------------------------------------
# Protocol timing (host control plane)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TimeoutConfig:
    """Timing block — mirrors the ``dare_global_config`` section of
    ``nodes.local.cfg`` (reference ``target/nodes.local.cfg:22-35``,
    parsed by ``src/config-comp/config-dare.c:20-44``).

    Values are seconds. The defaults mirror the reference's DEBUG profile
    (hb 10 ms, election 100–300 ms); the production profile in the reference
    is hb 1 ms, election 10–30 ms.
    """

    hb_period: float = 0.010
    elec_timeout_low: float = 0.100
    elec_timeout_high: float = 0.300
    retransmit_period: float = 0.040
    rc_info_period: float = 0.050      # membership/bootstrap gossip period
    log_pruning_period: float = 0.050

    @classmethod
    def production(cls) -> "TimeoutConfig":
        return cls(hb_period=0.001, elec_timeout_low=0.010,
                   elec_timeout_high=0.030)


# ---------------------------------------------------------------------------
# Cluster identity / membership
# ---------------------------------------------------------------------------

MAX_SERVER_COUNT = 13   # reference src/include/dare/dare.h:26


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Per-instance identity + group shape.

    The reference passes these through env vars (``server_idx``,
    ``group_size``, ``server_type``, ``config_path``, ``dare_log_file``,
    ``mgid`` — ``src/proxy/proxy.c:33-59``); :meth:`from_env` accepts the
    same names so drivers written against the reference's launch convention
    (``benchmarks/run.sh:24-33``) keep working.
    """

    server_idx: int = 0
    group_size: int = 3
    server_type: str = "start"          # "start" | "join"
    config_path: Optional[str] = None
    log_file: Optional[str] = None
    # DCN bootstrap: "host:port" of every replica's control endpoint
    # (the analog of the IB multicast group, dare_ibv_ud.h:25).
    peers: tuple = ()

    def __post_init__(self) -> None:
        if not (1 <= self.group_size <= MAX_SERVER_COUNT):
            raise ValueError(
                f"group_size must be in [1, {MAX_SERVER_COUNT}]")
        if self.server_type not in ("start", "join"):
            raise ValueError("server_type must be 'start' or 'join'")

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "ClusterConfig":
        e = os.environ if env is None else env
        return cls(
            server_idx=int(e.get("server_idx", 0)),
            group_size=int(e.get("group_size", 3)),
            server_type=e.get("server_type", "start"),
            config_path=e.get("config_path"),
            log_file=e.get("dare_log_file"),
            peers=tuple(p for p in e.get("peers", "").split(",") if p),
        )

    @property
    def majority(self) -> int:
        return self.group_size // 2 + 1


# ---------------------------------------------------------------------------
# Config file loading (the libconfig nodes.local.cfg analog, JSON format)
# ---------------------------------------------------------------------------

def load_config(path: str, env: Optional[dict] = None):
    """Load a cluster config file — the analog of ``dare_read_config`` +
    ``proxy_read_config`` over ``nodes.local.cfg`` (reference
    ``src/config-comp/``), in JSON::

        {
          "log":     {"n_slots": 16384, "slot_bytes": 256, ...},
          "timing":  {"hb_period": 0.001, "elec_timeout_low": 0.01, ...},
          "cluster": {"group_size": 3, "peers": ["h0:9000", ...], ...}
        }

    Per-instance identity still comes from env vars (``server_idx`` etc.),
    exactly like the reference. Returns (LogConfig, TimeoutConfig,
    ClusterConfig)."""
    import json

    with open(path) as f:
        raw = json.load(f)
    log_cfg = LogConfig(**raw.get("log", {}))
    timing = TimeoutConfig(**raw.get("timing", {}))
    cluster_raw = dict(raw.get("cluster", {}))
    if "peers" in cluster_raw:
        cluster_raw["peers"] = tuple(cluster_raw["peers"])
    e = os.environ if env is None else env
    if "server_idx" in e:
        cluster_raw["server_idx"] = int(e["server_idx"])
    if "group_size" in e:
        cluster_raw["group_size"] = int(e["group_size"])
    if "server_type" in e:
        cluster_raw["server_type"] = e["server_type"]
    if "dare_log_file" in e:
        cluster_raw["log_file"] = e["dare_log_file"]
    cluster_raw["config_path"] = path
    return log_cfg, timing, ClusterConfig(**cluster_raw)
