"""Epoch machinery shared by the txn coordinator and topology.

"Reconfigurable Atomic Transaction Commit" (arXiv:1906.01365) frames
both problems the same way: a host-side actor submits records to a
replicated log and must prove they COMMITTED — knowing that any
leader change between append and proof can silently overwrite the
suffix the record sat on. PR 17's coordinator grew exactly that
machinery (deposition detection via per-group last-seen terms,
record-term completion proofs, forget-and-retry under the same
exactly-once stamp); topology transitions need the identical rules
for their seeding writes. This module is that machinery factored out
— ONE copy, two users (``txn/coordinator.py``,
``topology/transition.py``) — plus the epoch counter topology fences
its cutovers with.

Everything here is host-pure (no jax — graftlint-enforced): these are
decision rules over step-output scalars, not device code.
"""

from __future__ import annotations

from typing import List

# Completion status of one stamped record placement
# (:func:`placement_status`).
PENDING = "pending"            # not yet provably committed — keep waiting
COMPLETE = "complete"          # committed under the append term: durable
INVALIDATED = "invalidated"    # append term deposed: forget, retry stamp

# Retry patience (steps) before a submitted-but-unplaced record is
# resubmitted — covers a deposed/mis-hinted leader that dropped the
# submission (per-stamp dedup keeps every retry exactly-once).
RETRY_STEPS = 4


def commit_frontier(res, rebased_total) -> List[int]:
    """Per-group ABSOLUTE commit frontier from one step's outputs
    (max over replicas — commit indices are quorum facts, any
    replica's is valid), rebase-corrected into the absolute domain."""
    import numpy as np
    commit = np.asarray(res["commit"])
    return [int(commit[g].max()) + int(rebased_total[g])
            for g in range(commit.shape[0])]


def term_now(res) -> List[int]:
    """Per-group current term from one step's outputs (max over
    replicas — terms only advance, so the max is the freshest)."""
    import numpy as np
    term = np.asarray(res["term"])
    return [int(term[g].max()) for g in range(term.shape[0])]


def placement_status(index: int, wterm: int, commit_abs_g: int,
                     term_now_g: int) -> str:
    """Completion rule for ONE stamped record whose append was
    observed at absolute ``index`` under term ``wterm`` (``index < 0``
    = submitted, placement not yet seen).

    * ``COMPLETE`` — the group's commit frontier passed the index
      while the append term still rules: majority-replicated under an
      unchanged leadership, nothing can have overwritten it.
    * ``INVALIDATED`` — the term advanced past ``wterm``: the append
      may sit on a deposed leader's overwritten suffix, so a later
      frontier past its index proves NOTHING. The caller must forget
      the placement and retry under the SAME stamp — if the record
      DID commit, dedup makes the retry a no-op.
    * ``PENDING`` — otherwise (including ``index < 0``).
    """
    if index < 0:
        return PENDING
    if index < commit_abs_g and term_now_g == wterm:
        return COMPLETE
    if term_now_g > wterm:
        return INVALIDATED
    return PENDING


class TermWatch:
    """Per-group deposition detector: remember the max term each
    group's in-flight appends were observed under; a current term
    above it means the leadership that accepted them is gone and
    un-committed appends may be overwritten.

    Pure bookkeeping — the OWNER's lock guards it (both users mutate
    only under their coordinator/controller lock)."""

    def __init__(self, n_groups: int):
        self._seen = [0] * int(n_groups)

    def reset(self, g: int) -> None:
        """Forget ``g`` — call when a fresh batch of appends goes out
        (the watch is per-batch, not per-lifetime)."""
        self._seen[g] = 0

    def note(self, g: int, term: int) -> None:
        """An append on ``g`` was observed under ``term``."""
        self._seen[g] = max(self._seen[g], int(term))

    def seen(self, g: int) -> int:
        return self._seen[g]

    def deposed(self, g: int, term_now_g: int) -> bool:
        """True iff ``g`` accepted appends under some term and its
        current term has advanced past it. Zero ``seen`` (nothing
        appended yet / just reset) never reports deposition."""
        return bool(self._seen[g]) and int(term_now_g) > self._seen[g]


class EpochClock:
    """The topology epoch: a monotone counter bumped at every cutover
    (in lock-step with ``KeyRouter.version``). Routing decisions and
    txn admissions carry the epoch they were made under; a mismatch at
    a later fence is the deterministic "the world moved" signal.

    Pure bookkeeping — the owning controller's lock guards bumps."""

    def __init__(self, start: int = 0):
        self._epoch = int(start)

    def current(self) -> int:
        return self._epoch

    def bump(self) -> int:
        self._epoch += 1
        return self._epoch
