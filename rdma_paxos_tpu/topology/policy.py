"""Load-driven topology policy — the loop that decides WHEN to split
and merge.

The mechanism lives in :mod:`~rdma_paxos_tpu.topology.transition`;
this module closes the loop the way every other actuator in the repo
does (``RepairController.on_alert``, the governor's SLO shed): a
feedback observer exports device-truth load gauges, stock
``AlertEngine`` rules fire on sustained conditions, and the
``add_hook`` callback turns a fire transition into a proposal.

Signals — all derived from the per-group COMMIT frontier (device
truth: what the groups actually committed, not what clients offered):

* ``topology_group_share{group=g}`` — group ``g``'s share of the
  committed work over a trailing step window.
* ``topology_skew`` — the hottest group's share normalized to the
  fair share ``1/G`` (2.0 = one group doing double its share).
* ``topology_override_load`` — the COLDEST policy-installed override
  group's normalized share (``G`` — i.e. never cold — while the
  policy owns no installed rules, so the merge rule stays silent).

Stock rules (``stock_rules()``, registered via ``alerts.add_rule`` by
``attach_topology``): sustained skew above ``skew_ratio`` fires the
split rule; a policy-owned override group sustained below
``cold_ratio`` fires the merge rule. ``for_evals`` is the hysteresis
— a one-eval spike never reshapes the keyspace.

Proposals: split carves the hot group's upper key half —
``[median_key, last_key + b"\\x00")`` of the keys it authoritatively
owns — into the least-loaded group. (Byte-range capture caveat: other
groups' keys falling inside that interval migrate too; the transition
seeds them correctly, the policy just pays a bigger window.) Merge
returns the coldest policy-installed rule's range to its ring owners.
Both consult the governor first — no proposal while the SLO shed
latch is up (a latency incident is the wrong moment to add seeding
traffic) — and sit out the policy's own eval-domain cooldown on top
of the controller's step-domain one. The policy only ever merges
rules it itself installed (``_mine``): operator-pinned overrides are
never touched.

Host-pure module: never imports jax or numpy (frontier math is plain
ints via the shared :mod:`~rdma_paxos_tpu.topology.epoch` helpers),
adds no STEP_CACHE keys — ``analysis/purity.py`` enforces it.
"""

from __future__ import annotations

import collections
import threading
from typing import Deque, List, Optional, Tuple

from rdma_paxos_tpu.obs.alerts import WARN
from rdma_paxos_tpu.shard.router import RangeRule
from rdma_paxos_tpu.topology import epoch as _epoch

SPLIT_RULE = "topology_group_skew"
MERGE_RULE = "topology_group_cold"


class TopologyPolicy:
    """Observes per-group committed-work shares and proposes
    split/merge transitions through an attached
    :class:`~rdma_paxos_tpu.topology.transition.TopologyController`.

    ``observe(cluster, res)`` rides the controller's finish()-tail
    hook (readback thread); ``on_alert`` rides the AlertEngine's fire
    transitions (driver cadence thread). Lock order: the policy lock
    is OUTERMOST — proposals are issued with it released, so
    ``policy._lock -> controller._lock -> cluster._host_lock`` never
    inverts.
    """

    def __init__(self, ctl=None, *, window: int = 32,
                 skew_ratio: float = 2.0, cold_ratio: float = 0.5,
                 for_evals: int = 4, cooldown_evals: int = 16,
                 min_keys: int = 4):
        self.ctl = None
        self.skew_ratio = float(skew_ratio)
        self.cold_ratio = float(cold_ratio)
        self.for_evals = int(for_evals)
        self.cooldown_evals = int(cooldown_evals)
        self.min_keys = int(min_keys)
        self._window = int(window)
        self.proposals = 0
        self.vetoes = 0
        self._lock = threading.Lock()
        # eval counter (one per observe pass — the hysteresis/cooldown
        # time base)  # guarded-by: _lock [writes]
        self._evals = 0
        # no proposal before this eval (policy-level cooldown)
        # guarded-by: _lock [writes]
        self._gate_after = 0
        # previous absolute commit frontier (per group)
        # guarded-by: _lock [writes]
        self._frontier_prev: Optional[List[int]] = None
        # trailing per-group committed-entry deltas
        # guarded-by: _lock [writes]
        self._loadwin: List[Deque[int]] = []
        # last computed per-group shares  # guarded-by: _lock [writes]
        self._shares: List[float] = []
        # override rules THIS policy proposed (merge candidates; pruned
        # once no longer installed)  # guarded-by: _lock [writes]
        self._mine: List[RangeRule] = []
        if ctl is not None:
            self.bind(ctl)
        from rdma_paxos_tpu.analysis import runtime_guard
        runtime_guard.maybe_guard(self, "_lock", __file__)

    def bind(self, ctl) -> None:
        self.ctl = ctl
        with self._lock:
            self._loadwin = [collections.deque(maxlen=self._window)
                             for _ in range(ctl.G)]
            self._shares = [1.0 / ctl.G] * ctl.G

    # ---------------- stock rules ----------------

    def stock_rules(self) -> List[dict]:
        """The skew/cold rule pair ``attach_topology`` registers.
        Plain dicts — they ride health snapshots like every other
        rule, and the names are the hook-dispatch contract."""
        return [
            dict(name=SPLIT_RULE, severity=WARN, kind="gauge_cmp",
                 metric="topology_skew", op=">",
                 value=self.skew_ratio, for_evals=self.for_evals),
            dict(name=MERGE_RULE, severity=WARN, kind="gauge_cmp",
                 metric="topology_override_load", op="<",
                 value=self.cold_ratio, for_evals=self.for_evals),
        ]

    # ---------------- the feedback pass ----------------

    def observe(self, cluster, res) -> None:
        """One evaluation: fold the finished step's commit-frontier
        advance into the trailing window and export the load gauges
        the stock rules evaluate."""
        ctl = self.ctl
        if ctl is None:
            return
        frontier = [int(v) for v in _epoch.commit_frontier(
            res, cluster.rebased_total)]
        overrides = ctl.kvs.router.overrides    # atomic list read
        with self._lock:
            self._evals += 1
            if (self._frontier_prev is not None
                    and len(self._frontier_prev) == len(frontier)):
                for g, (cur, prev) in enumerate(
                        zip(frontier, self._frontier_prev)):
                    self._loadwin[g].append(max(0, cur - prev))
            self._frontier_prev = frontier
            sums = [sum(w) for w in self._loadwin]
            total = sum(sums)
            if total > 0:
                self._shares = [s / total for s in sums]
            shares = list(self._shares)
            if not ctl.in_window():
                # a proposed-then-abandoned rule never installed (and
                # a merged one just uninstalled): stop tracking it
                self._mine = [r for r in self._mine if r in overrides]
            mine = list(self._mine)
        G = len(shares)
        obs = ctl.obs
        if obs is not None:
            for g, s in enumerate(shares):
                obs.metrics.set("topology_group_share", round(s, 4),
                                group=g)
            obs.metrics.set("topology_skew", round(max(shares) * G, 4))
            installed = [r for r in mine if r in overrides]
            obs.metrics.set(
                "topology_override_load",
                round(min((shares[r.group] * G for r in installed),
                          default=float(G)), 4))

    # ---------------- alert → proposal ----------------

    def on_alert(self, name: str, severity: str) -> None:
        """AlertEngine fire-transition hook (``add_hook``): dispatch
        to the proposal matching the fired stock rule. Exceptions are
        the engine's problem to swallow; this path never raises on a
        refused proposal — refusal IS the hysteresis."""
        if name == SPLIT_RULE:
            self._try_split()
        elif name == MERGE_RULE:
            self._try_merge()

    def _governor_vetoes(self) -> bool:
        """Consult the governor: while the SLO shed latch is up the
        cluster is in a latency incident — seeding traffic and a
        freeze window would pour fuel on it."""
        gov = getattr(self.ctl.cluster, "governor", None)
        if gov is not None and gov.decision.shed:
            self.vetoes += 1
            return True
        return False

    def _cooling(self) -> bool:
        with self._lock:
            return self._evals < self._gate_after

    def _note_proposed(self, rule: Optional[RangeRule]) -> None:
        with self._lock:
            self._gate_after = self._evals + self.cooldown_evals
            if rule is not None:
                self._mine.append(rule)
        self.proposals += 1

    def _try_split(self) -> None:
        ctl = self.ctl
        if ctl is None or self._cooling() or self._governor_vetoes():
            return
        with self._lock:
            shares = list(self._shares)
        if len(shares) < 2:
            return
        hot = max(range(len(shares)), key=lambda g: shares[g])
        target = min((g for g in range(len(shares)) if g != hot),
                     key=lambda g: shares[g])
        rng = self._median_range(hot)
        if rng is None:
            return
        lo, hi = rng
        if ctl.propose_split(lo, hi, target):
            self._note_proposed(RangeRule(lo, hi, target))

    def _try_merge(self) -> None:
        ctl = self.ctl
        if ctl is None or self._cooling() or self._governor_vetoes():
            return
        with self._lock:
            shares = list(self._shares)
            mine = list(self._mine)
        G = len(shares)
        installed = [r for r in mine if r in ctl.kvs.router.overrides]
        cold = [r for r in installed
                if shares[r.group] * G < self.cold_ratio]
        if not cold:
            return
        rule = min(cold, key=lambda r: shares[r.group])
        try:
            if ctl.propose_merge(rule):
                self._note_proposed(None)
        except ValueError:
            pass        # uninstalled since the check — nothing to do

    def _median_range(self, hot: int) -> Optional[Tuple[bytes, bytes]]:
        """The hot group's upper key half as a byte range: ``[median,
        last + b"\\x00")`` over the keys it authoritatively owns
        today. None when the group holds too few keys for a split to
        mean anything."""
        ctl = self.ctl
        kvs = ctl.kvs
        lead = ctl.cluster.leader_hint(hot)
        if lead < 0:
            lead = 0
        keys = sorted(
            k for k, _v in kvs.groups[hot].items_in_range(lead, b"",
                                                          None)
            if kvs.router.group_of(k) == hot)
        if len(keys) < self.min_keys:
            return None
        return keys[len(keys) // 2], keys[-1] + b"\x00"

    # ---------------- export ----------------

    def status(self) -> dict:
        with self._lock:
            return dict(
                evals=self._evals,
                shares=[round(s, 4) for s in self._shares],
                proposals=self.proposals,
                vetoes=self.vetoes,
                cooldown_after=self._gate_after,
                rules=[r.to_dict() for r in self._mine],
            )
