"""Elastic topology control plane — online group split/merge.

The router's group count is frozen at construction (G is baked into
the stacked ``[G, R, ...]`` device state and ONE compiled dispatch
steps all of it), so a hot group used to be a permanent throughput
ceiling. This package lifts that ceiling WITHOUT touching the device:
a "split" carves the hot key range out of its group via a router
range-override rule (the operator escape hatch ``shard/router.py``
documents for exactly this), a "merge" removes the rule — splits
reshape host-side routing, never the compiled dispatch, so STEP_CACHE
keys and step outputs stay bit-identical with topology attached
(pinned by test).

Three pieces, mirroring the reconfigurable-commit framing
(arXiv:1906.01365) and DXRAM's load-directed shard migration
(arXiv:1807.03562):

* :mod:`~rdma_paxos_tpu.topology.epoch` — the term-watch/completion-
  proof machinery factored OUT of the txn coordinator and shared by
  both subsystems: deposition detection, record-term completion
  proofs, forget-and-retry under the same stamp. One copy, two users.
* :mod:`~rdma_paxos_tpu.topology.transition` — the two-router
  transition window: live range keys are seeded into their new owner
  groups through exactly-once stamped PUTs with epoch-proofed
  completion, digests verified donor-vs-target, writes to the
  migrating range frozen (queued, step-domain deadline) only for the
  final cutover, leases on affected groups revoked before the router
  swap and re-granted after. Merge is the same window run in reverse.
* :mod:`~rdma_paxos_tpu.topology.policy` — the load-driven loop:
  per-group committed-work shares (device-truth commit frontiers)
  export as gauges, a stock ``AlertEngine`` rule fires on sustained
  skew, and the ``add_hook`` policy proposes split/merge with
  hysteresis and a cooldown — the ``RepairController.on_alert`` /
  governor-shed pattern.

Every transition is an epoch bump fenced through the drained-serial
path repair already uses: the controller's ``needs_drain()`` gates
the drivers' pipelining, ``drive()`` runs on the stepping thread with
zero dispatches in flight.
"""

from __future__ import annotations


def attach_topology(kvs, *, policy=None, obs=None, alerts=None,
                    **opts) -> "TopologyController":
    """Build a :class:`TopologyController` over ``kvs`` (a
    ``ShardedKVS``) and hang it on ``cluster.topology`` — the finish()
    tail starts feeding it, the drivers' drain gates see it through
    the same attach point leases/repair/governor use. ``policy=True``
    (or a prebuilt :class:`~rdma_paxos_tpu.topology.policy.
    TopologyPolicy`) attaches the load loop; with ``alerts=`` its
    skew rules are registered and the proposal hook is wired."""
    from rdma_paxos_tpu.topology.transition import TopologyController
    ctl = TopologyController(kvs, obs=obs, **opts)
    kvs.shard.topology = ctl
    if policy:
        from rdma_paxos_tpu.topology.policy import TopologyPolicy
        if policy is True:
            policy = TopologyPolicy(ctl)
        else:
            policy.bind(ctl)
        ctl.policy = policy
        if alerts is not None:
            for rule in policy.stock_rules():
                if rule["name"] not in {r["name"] for r in alerts.rules}:
                    alerts.add_rule(rule)
            alerts.add_hook(policy.on_alert)
    return ctl
