"""Topology nemesis — a split mid-leader-crash plus a merge, proven
linearizable.

The shard nemesis proves faults stay inside their group; the txn
nemesis proves cross-group atomicity survives them. This runner
proves the NEW claim: an elastic transition window that a fault lands
in the middle of never costs a linearizability violation — the window
either completes (seed records epoch-retried under the new term) or
abandons (nothing served ever moved), and either verdict is
deterministic per seed.

One seeded run over a governed sharded cluster with leases attached:

* closed-loop session writes per group (per-key Wing–Gong history),
  the target group's range carrying the hot keys;
* a **split** of the hot group's upper key half is proposed
  mid-workload, and the hot group's LEADER is fail-stopped while the
  window is open (seed records in flight) — re-elected a few steps
  later, the window finishes under the new term;
* after settling, a **merge** returns the range to its ring owners;
* the verdict demands: zero per-group invariant violations, a clean
  Wing–Gong history, both transitions completed (or a deterministic
  abandon — asserted exactly), and the lease fence PROVEN from the
  trace ring: every affected group has LEASE_REVOKED
  (reason=topology_cutover) sequenced BEFORE its TOPOLOGY_CUTOVER
  event and LEASE_GRANTED after it.

Single-threaded embedding contract: the runner both steps the
cluster and issues writes, so it must never call a blocking put on a
frozen range — it consults ``TopologyController.would_block`` and
defers the write instead (the gate exists for multi-threaded
drivers). A retransmit whose key's group moved at cutover is retired
as ambiguous (fate unknown) and a FRESH write issued — the dedup
stream is per-(conn, group), so a verbatim resend into a different
group would be a new op wearing an old op's id.

Determinism: all randomness derives from the seed; time is the
logical step counter — same seed, same verdict.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from rdma_paxos_tpu.chaos.faults import LinkModel
from rdma_paxos_tpu.chaos.history import HistoryRecorder
from rdma_paxos_tpu.chaos.invariants import (
    InvariantChecker, InvariantViolation)
from rdma_paxos_tpu.chaos.linearize import check_history
from rdma_paxos_tpu.chaos.runner import DEFAULT_KV_CFG
from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.obs import trace as obs_trace
from rdma_paxos_tpu.runtime import reads as _reads
from rdma_paxos_tpu.runtime.governor import attach_governor
from rdma_paxos_tpu.shard.chaos import keys_for_groups
from rdma_paxos_tpu.shard.cluster import ShardedCluster
from rdma_paxos_tpu.shard.kvs import ShardedKVS
from rdma_paxos_tpu.shard.router import RangeRule
from rdma_paxos_tpu.topology import attach_topology


class TopologyNemesisRunner:
    """One seeded split-mid-crash + merge run over a fresh governed
    sharded cluster."""

    def __init__(self, cfg: Optional[LogConfig] = None,
                 n_replicas: int = 3, n_groups: int = 3, *,
                 seed: int = 0, steps: int = 120, split_step: int = 24,
                 crash_step: int = 25, reelect_after: int = 4,
                 merge_step: int = 72, target_group: int = 0,
                 settle_steps: int = 24, governor: bool = True,
                 obs=None):
        self.cfg = cfg or DEFAULT_KV_CFG
        self.R, self.G = int(n_replicas), int(n_groups)
        self.seed = int(seed)
        self.steps = int(steps)
        self.split_step = int(split_step)
        self.crash_step = int(crash_step)
        self.reelect_after = int(reelect_after)
        self.merge_step = int(merge_step)
        self.target = int(target_group)
        self.settle_steps = int(settle_steps)
        self.shard = ShardedCluster(self.cfg, self.R, self.G)
        if obs is None:
            from rdma_paxos_tpu.obs import Observability
            obs = Observability()
        self.obs = obs
        self.shard.obs = obs
        self.kv = ShardedKVS(self.shard, cap=256)
        _reads.attach(self.shard)
        self.ctl = attach_topology(self.kv, obs=obs,
                                   cooldown_steps=8)
        self.governor = (attach_governor(self.shard, obs=obs)
                         if governor else None)
        self.link = LinkModel(self.R, seed=seed)
        self.shard.link_models[self.target] = self.link
        self.checkers = [InvariantChecker(self.R)
                         for _ in range(self.G)]
        # hot keys: a larger pool in the target group (its upper half
        # is what the split carves out)
        self.keys = keys_for_groups(self.kv.router, 4)
        self.keys[self.target] = keys_for_groups(
            self.kv.router, 8, prefix=b"hot")[self.target]
        self.rng = random.Random(f"topology-nemesis:{seed}")
        self._vn = 0
        self.history = HistoryRecorder()
        for g in range(self.G):
            self.kv.groups[g].history = self.history
        self.sess = self.kv.session(1)
        self._out: List[Optional[dict]] = [None] * self.G
        self.write_patience = 14
        self._rule = None       # the installed split rule (for merge)

    # ------------------------------------------------------------------

    def _split_range(self):
        """Deterministic hot range: the upper half of the target
        group's (sorted) key pool, carved into the next group."""
        hks = sorted(self.keys[self.target])
        lo = hks[len(hks) // 2]
        hi = hks[-1] + b"\x00"
        dst = (self.target + 1) % self.G
        return lo, hi, dst

    def _issue(self, t: int) -> None:
        """Closed-loop session write per ORIGINAL group slot (one
        outstanding each): retransmit on failover, patience →
        ambiguous, frozen-range writes deferred, moved-group
        retransmits retired as ambiguous + reissued fresh."""
        for g in range(self.G):
            out = self._out[g]
            if out is not None:
                cur_g = self.kv.group_of(out["key"])
                if t - out["issued"] > self.write_patience:
                    self.history.timeout(out["op_id"])   # fate unknown
                    self._out[g] = None
                elif cur_g != out["group"]:
                    # the key's group moved at cutover while this op
                    # was in flight: its donor-log fate rode the
                    # seeded transfer — ambiguous, never resent
                    # verbatim into the new group's dedup stream
                    self.history.timeout(out["op_id"])
                    self._out[g] = None
                else:
                    lead = self.shard.leader_hint(cur_g)
                    if lead >= 0 and lead != out["to"]:
                        out["to"] = lead
                        self.sess.retransmit_put(
                            out["key"], out["val"], out["req_id"],
                            leader=lead)
            if self._out[g] is None:
                key = self.rng.choice(self.keys[g])
                if self.ctl.would_block(key):
                    continue        # frozen range — defer, don't wedge
                kg = self.kv.group_of(key)
                lead = self.shard.leader_hint(kg)
                if lead < 0:
                    continue
                self._vn += 1
                val = b"v%d" % self._vn
                _, rid = self.sess.put(key, val, leader=lead)
                op_id = self.history.op_id_for(
                    self.sess.conn_for(kg), rid)
                self._out[g] = dict(key=key, val=val, req_id=rid,
                                    op_id=op_id, to=lead, issued=t,
                                    group=kg)

    def _observe_clients(self, t: int) -> None:
        for g in range(self.G):
            out = self._out[g]
            if out is None:
                continue
            gg = out["group"]       # the log it was submitted into
            lead = self.shard.leader_hint(gg)
            if lead < 0:
                continue
            self.kv.groups[gg]._fold(lead)
            marks = self.kv.groups[gg].last_req[lead]
            if marks.get(self.sess.conn_for(gg), 0) >= out["req_id"]:
                self.history.ok(out["op_id"])
                self._out[g] = None

    def _check(self, res, t: int, violations: List[dict]) -> None:
        for g in range(self.G):
            try:
                self.checkers[g].check_step(
                    {k: res[k][g] for k in ("commit", "role", "term",
                                            "head", "apply", "end")},
                    step=t,
                    rebased_total=int(self.shard.rebased_total[g]))
            except InvariantViolation as v:
                d = v.as_dict()
                d["group"] = g
                violations.append(d)

    def _lease_fence_proof(self) -> Dict:
        """Reconstruct the fence ordering from the trace ring: for
        EVERY cutover, every affected group must show LEASE_REVOKED
        (reason=topology_cutover) with a ring seq BEFORE the cutover's
        and LEASE_GRANTED after it."""
        evs = self.obs.trace.events()
        cutovers = [e for e in evs if e.kind == obs_trace.TOPOLOGY_CUTOVER]
        missing: List[dict] = []
        for cut in cutovers:
            affected = set(cut.fields.get("donors", ())) \
                | set(cut.fields.get("targets", ()))
            for g in sorted(affected):
                revoked = any(
                    e.seq < cut.seq
                    and e.kind == obs_trace.LEASE_REVOKED
                    and e.fields.get("group") == g
                    and e.fields.get("reason") == "topology_cutover"
                    for e in evs)
                granted = any(
                    e.seq > cut.seq
                    and e.kind == obs_trace.LEASE_GRANTED
                    and e.fields.get("group") == g
                    for e in evs)
                if not revoked:
                    missing.append(dict(cutover_seq=cut.seq, group=g,
                                        missing="revoke_before"))
                if not granted:
                    missing.append(dict(cutover_seq=cut.seq, group=g,
                                        missing="grant_after"))
        return dict(ok=not missing and bool(cutovers),
                    cutovers=len(cutovers), missing=missing)

    def _tick(self, t: int, violations: List[dict],
              timeouts: Optional[Dict[int, list]] = None) -> None:
        self.history.set_clock(t)
        self._issue(t)
        res = self.shard.step(timeouts=timeouts or {})
        self._observe_clients(t)
        self._check(res, t, violations)
        # the drained-serial pass the drivers' _drain_admin runs: in
        # this lockstep harness every step boundary is drained
        self.ctl.drive()

    def run(self) -> Dict:
        violations: List[dict] = []
        self.shard.place_leaders()
        crashed = -1
        for t in range(self.steps):
            timeouts: Dict[int, list] = {}
            if t == self.split_step:
                lo, hi, dst = self._split_range()
                assert self.ctl.propose_split(lo, hi, dst)
                self._rule = RangeRule(lo, hi, dst)
            if t == self.crash_step:
                crashed = self.shard.leader_hint(self.target)
                if crashed >= 0:
                    self.link.down.add(crashed)     # fail-stop, silent
            if (crashed >= 0
                    and t == self.crash_step + self.reelect_after):
                cand = next(r for r in range(self.R) if r != crashed)
                timeouts[self.target] = [cand]
            if t == self.merge_step:
                if self._rule in self.kv.router.overrides:
                    self.ctl.propose_merge(self._rule)
            self._tick(t, violations, timeouts)
        if crashed >= 0:
            self.link.down.discard(crashed)
        self.link.heal()
        for t in range(self.steps, self.steps + self.settle_steps):
            self._tick(t, violations)
        self.history.set_clock(self.steps + self.settle_steps)
        for op_id in self.history.pending():
            self.history.timeout(op_id)
        for g in range(self.G):
            try:
                self.checkers[g].check_convergence(
                    self.shard.replayed[g])
            except InvariantViolation as v:
                d = v.as_dict()
                d["group"] = g
                violations.append(d)
        linz = check_history(self.history.ops())
        fence = self._lease_fence_proof()
        topo = self.ctl.status()
        new_leader = self.shard.leader_hint(self.target)
        ok = (not violations and linz["ok"] is True
              and fence["ok"]
              and topo["transitions_total"] == 2
              and topo["abandoned_total"] == 0
              and topo["phase"] == "idle"
              and not self.kv.router.overrides
              and new_leader >= 0 and new_leader != crashed)
        return dict(
            ok=ok, seed=self.seed, steps=self.steps,
            target_group=self.target, crashed_leader=crashed,
            new_leader=new_leader,
            invariant_violations=violations,
            linearizability=dict(ok=linz["ok"],
                                 violations=linz["violations"],
                                 undecided=linz["undecided"],
                                 ops=linz["ops"]),
            lease_fence=fence,
            topology=dict(
                transitions=topo["transitions_total"],
                abandoned=topo["abandoned_total"],
                epoch=topo["epoch"],
                router_version=topo["router_version"],
                overrides=len(self.kv.router.overrides)),
            governor=(self.governor.status()
                      if self.governor is not None else None),
        )


def run_topology_chaos(seed: int = 0, **kw) -> Dict:
    """One seeded topology-nemesis run; same seed, same verdict."""
    return TopologyNemesisRunner(seed=seed, **kw).run()
