"""The two-router transition window — online split/merge execution.

A transition migrates one key RANGE between groups with zero
linearizability violations, while clients keep writing. The trick is
that nothing ever serves a half-moved range: the LIVE router keeps
routing every key to its old owner until one atomic cutover, and the
window works off a CANDIDATE router (the live one ± exactly one
range-override rule) that nothing serves — it only answers "where
will this key live AFTER the cutover". Split installs the rule, merge
removes it; both directions are the same window because every
decision is a diff between the two routers:

    for every live in-range key k:
        src = live.group_of(k)        # authoritative copy today
        dst = candidate.group_of(k)   # owner after cutover
        src != dst  ⟹  (k, v) must be seeded into dst

The window phases (exported in ``status()``, drawn in the console):

  IDLE ──propose──▶ SEED ──converged──▶ FREEZE ──verified──▶ CUTOVER
                      ▲                    │ (deadline/repair)    │
                      └────── deltas ◀─────┴──abandon──▶ IDLE     ▼
                                                          IDLE + cooldown

* **SEED / catch-up** — on each drained-serial ``drive()`` pass the
  donors' tables are enumerated (``items_in_range``) and diffed
  against the targets' tables; missing/stale pairs are copied as
  exactly-once stamped PUT records (per-record conn ids, the txn
  coordinator's stamping recipe), stale target copies are deleted.
  Completion of every record is epoch-proofed (``topology/epoch`` —
  committed under an unchanged term, INVALIDATED placements retried
  under the same stamp), so seeding survives donor/target failovers.
  Writes to the range stay OPEN — they land on donors and the next
  pass picks them up.
* **FREEZE** — once a pass finds zero deltas, new writes to the
  migrating range queue at the client gate (``gate_key``); the few
  pre-freeze writes still in the pipeline drain, the next passes copy
  the final deltas. Freeze is bounded by a step-domain deadline —
  blown deadline abandons the window (unfreeze, nothing served ever
  moved, orphaned seed copies are reconciled or deleted by the next
  window over the range).
* **CUTOVER** — with dispatches drained (``require_drained``), zero
  deltas, digests verified donor-vs-target, no live txns and no
  repair on the affected groups: leases on every affected group are
  revoked FIRST (the trace ring orders LEASE_REVOKED before
  TOPOLOGY_CUTOVER — the chaos proof), then the live router's
  override table is swapped atomically and ``version`` bumps with the
  topology epoch. The drivers' cutover hook fails donor in-flight
  waiters and unpins their conns; the txn coordinator's
  router-version check aborts any straggler. Unfreeze, re-granting
  happens naturally once the lease barrier lapses.

Old-owner copies left behind a split are orphans the router can no
longer reach — invisible to every reader, hence harmless, and the
reverse (merge) window deletes them as stale target copies.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Tuple

from rdma_paxos_tpu.models.kvs import OP_PUT, OP_RM, encode_cmd
from rdma_paxos_tpu.obs import trace as obs_trace
from rdma_paxos_tpu.shard.router import RangeRule, canon_key
from rdma_paxos_tpu.topology import epoch as _epoch

# window phases
IDLE = "idle"
SEED = "seed"          # copying / catch-up passes (range writes open)
FROZEN = "frozen"      # range writes queued; final deltas draining


def range_digest(items: List[Tuple[bytes, bytes]]) -> str:
    """Order-independent-input digest of a sorted ``(key, value)``
    list — the donor-vs-target agreement witness recorded in the
    TOPOLOGY_VERIFIED trace event (the repair pipeline's
    digest-verified-transfer idiom, host-side)."""
    h = hashlib.sha256()
    for k, v in items:
        h.update(len(k).to_bytes(4, "big") + k)
        h.update(len(v).to_bytes(4, "big") + v)
    return h.hexdigest()


class TopologyController:
    """Drives split/merge transition windows over a ``ShardedKVS``.

    Attached at ``cluster.topology`` (``attach_topology``): the
    finish() tail feeds ``note_appends``/``observe`` (record
    placement + completion proofs, off the readback thread), the
    drivers' ``_drain_admin`` calls ``drive()`` on drained-serial
    iterations (enumeration, freezing, cutover), and ``needs_drain``
    holds pipelining for the whole window — the same give-way
    contract repair uses."""

    # conn-id namespace base for seed records: far above real clients
    # AND the txn coordinator's 1<<20 (per-record conn = BASE + serial,
    # pushed through ShardedKVS.conn_for — unique forever, so the
    # fold's per-conn high-water dedup is exactly-once per record with
    # no FIFO assumption across records)
    SEED_CLIENT_BASE = 1 << 21

    def __init__(self, kvs, *, obs=None, deadline_steps: int = 2048,
                 freeze_deadline_steps: int = 256,
                 cooldown_steps: int = 64):
        self.kvs = kvs
        self.cluster = kvs.shard
        self.G = self.cluster.G
        self.obs = obs if obs is not None else getattr(
            self.cluster, "obs", None)
        self.deadline_steps = int(deadline_steps)
        self.freeze_deadline_steps = int(freeze_deadline_steps)
        self.cooldown_steps = int(cooldown_steps)
        self.policy = None                  # bound by attach_topology
        self.epoch = _epoch.EpochClock(self.kvs.router.version)
        self.transitions_total = 0
        self.abandoned_total = 0
        # ---- controller-lock discipline (runtime_guard-checked) ----
        # window phase (IDLE/SEED/FROZEN)  # guarded-by: _lock [writes]
        self._phase = IDLE
        # active transition: direction ("split"/"merge"), the rule
        # being installed/removed, and the candidate router
        # guarded-by: _lock [writes]
        self._direction: Optional[str] = None
        self._rule: Optional[RangeRule] = None       # guarded-by: _lock [writes]
        self._cand = None                            # guarded-by: _lock [writes]
        # absolute step bounds of the window / freeze / cooldown
        # guarded-by: _lock [writes]
        self._deadline = 0
        self._freeze_deadline = 0                    # guarded-by: _lock [writes]
        self._cooldown_until = 0                     # guarded-by: _lock [writes]
        # groups the active window touches (lease revocation set)
        # guarded-by: _lock [writes]
        self._affected: set = set()
        # in-flight seed records: (g, req) -> dict(kind, key, payload,
        # index, term, retry)  # guarded-by: _lock [writes]
        self._records: Dict[Tuple[int, int], dict] = {}
        # per-group stamped-request counter (rides the per-record conn
        # id, so it never resets)  # guarded-by: _lock [writes]
        self._req = [0] * self.G
        # per-group deposition watch for in-flight seed appends — the
        # SHARED epoch machinery (one copy with txn/coordinator.py)
        # guarded-by: _lock [writes]
        self._terms = _epoch.TermWatch(self.G)
        # digests of the last verified pass (status/trace export)
        # guarded-by: _lock [writes]
        self._last_digest: Dict[int, str] = {}
        # trace-plane ids of the ACTIVE and the LAST transition-window
        # trace. Written only under _lock; READ lock-free (plain
        # attribute load) by the txn coordinator when it blames a
        # TOPOLOGY abort on the window — the coordinator must never
        # take this lock (drive() calls txn.wants_serial() while
        # holding it: taking _lock from under the coordinator's lock
        # would be the ABBA inversion).
        # guarded-by: _lock [writes]
        self.window_trace: Optional[str] = None
        self.last_window_trace: Optional[str] = None  # guarded-by: _lock [writes]
        self._lock = threading.RLock()
        # client write gate: while a range is frozen, put/remove/txn
        # admissions for its keys wait here until cutover or abandon.
        # The frozen-range copy below is read under _gate_cv by client
        # threads and written under BOTH (_lock then _gate_cv) by the
        # drive/abandon paths.
        self._gate_cv = threading.Condition()
        # guarded-by: _gate_cv [writes]
        self._frozen_range: Optional[Tuple[bytes, Optional[bytes]]] = None
        from rdma_paxos_tpu.analysis import runtime_guard
        runtime_guard.maybe_guard(self, "_lock", __file__)

    def _tracer(self):
        """The shared trace plane, or None when span sampling is off
        (one switch silences spans AND subsystem traces)."""
        from rdma_paxos_tpu.obs.tracectx import active_tracer
        return active_tracer(self.obs)

    # ---------------- proposals ----------------

    def propose_split(self, lo, hi, group: int) -> bool:
        """Open a split window: install ``RangeRule(lo, hi, group)``
        at cutover, seeding every live in-range key into ``group``.
        Returns False (refused) while a window is open or cooling
        down."""
        return self._propose("split", RangeRule(lo, hi, group))

    def propose_merge(self, rule: RangeRule) -> bool:
        """Open a merge window: REMOVE an installed override rule at
        cutover, seeding the rule group's in-range keys back into
        their ring owners. The rule must be installed verbatim."""
        if rule not in self.kvs.router.overrides:
            raise ValueError(f"rule not installed: {rule!r}")
        return self._propose("merge", rule)

    def _propose(self, direction: str, rule: RangeRule) -> bool:
        with self._lock:
            if self._phase != IDLE:
                return False
            if self.cluster.step_index < self._cooldown_until:
                return False
            cand = (self.kvs.router.with_rule(rule)
                    if direction == "split"
                    else self.kvs.router.without_rule(rule))
            self._direction = direction
            self._rule = rule
            self._cand = cand
            self._deadline = self.cluster.step_index + self.deadline_steps
            self._affected = {rule.group}
            self._records.clear()
            self._last_digest = {}
            self._phase = SEED
            tr = self._tracer()
            if tr is not None:
                # TraceContext is leaf-locked: safe to call under _lock
                self.window_trace = tr.begin(
                    "topology", direction=direction,
                    group=rule.group, lo=rule.lo.hex(),
                    hi=rule.hi.hex() if rule.hi is not None else None)
        self._trace(obs_trace.TOPOLOGY_PROPOSED, direction=direction,
                    lo=rule.lo.hex(),
                    hi=rule.hi.hex() if rule.hi is not None else None,
                    group=rule.group, step=self.cluster.step_index)
        self._metric_inc("topology_proposed_total", direction=direction)
        return True

    # ---------------- driver / cluster surface ----------------

    def needs_drain(self) -> bool:
        """True for the whole window: transitions run on drained
        serial iterations only (the repair give-way contract)."""
        with self._lock:
            return self._phase != IDLE

    def in_window(self) -> bool:
        return self.needs_drain()

    def cooling(self) -> bool:
        """True while the post-window cooldown runs. The sharded
        driver's busy gate keeps stepping through it (64 fast
        iterations, bounded) — the cooldown is step-domain, and a
        PARKED driver's step index only advances at the idle
        heartbeat, which would stretch a 64-step cooldown into
        minutes of refused proposals."""
        with self._lock:
            return (self._phase == IDLE
                    and self.cluster.step_index < self._cooldown_until)

    def frozen(self) -> bool:
        with self._gate_cv:
            return self._frozen_range is not None

    def would_block(self, key) -> bool:
        """True when :meth:`gate_key` would block for ``key`` right
        now. Single-threaded embedders (the chaos runner steps the
        cluster and issues writes on ONE thread) must consult this
        and DEFER in-range writes while frozen — calling a blocking
        put from the only thread that can drive the unfreeze would
        wedge."""
        kb = canon_key(key)
        with self._gate_cv:
            fr = self._frozen_range
        if fr is None:
            return False
        lo, hi = fr
        return kb >= lo and (hi is None or kb < hi)

    def gate_key(self, key) -> None:
        """Client write gate: block while ``key`` is in a frozen
        migrating range (bounded — cutover or abandon always clears
        the freeze; the wait wakes on either). Called on client
        threads BEFORE any coordinator/cluster lock is taken."""
        kb = canon_key(key)
        with self._gate_cv:
            while True:
                fr = self._frozen_range
                if fr is None:
                    return
                lo, hi = fr
                if kb < lo or (hi is not None and kb >= hi):
                    return
                self._gate_cv.wait(timeout=0.05)

    def note_appends(self, g: int, r: int, take, term: int,
                     end_abs: int) -> None:
        """Stamp-loop hook (cluster.finish, outside the host lock —
        same ABBA contract as the txn coordinator's): learn each seed
        record's ``(term, index)`` placement."""
        with self._lock:
            if not self._records:
                return
            base = end_abs - len(take)
            for i, (_et, c, req, _p) in enumerate(take):
                rec = self._records.get((g, req))
                if rec is None or c != self._conn(g, req):
                    continue
                if rec["index"] < 0:
                    rec["index"] = base + i
                    rec["term"] = term
                    self._terms.note(g, term)

    def observe(self, cluster, res) -> None:
        """finish()-tail hook: epoch-proof seed-record completion
        (committed under an unchanged term), forget-and-retry
        INVALIDATED placements, resubmit dropped records — the same
        rules ``txn/coordinator._observe_decided`` applies, via the
        same shared module. The bound policy's load observer rides
        the same hook — BEFORE the controller lock (the policy lock
        is outermost, see its class doc)."""
        pol = self.policy
        if pol is not None:
            pol.observe(cluster, res)
        with self._lock:
            if self._phase == IDLE or not self._records:
                return
            commit_abs = _epoch.commit_frontier(
                res, self.cluster.rebased_total)
            term_now = _epoch.term_now(res)
            for (g, req), rec in list(self._records.items()):
                st = _epoch.placement_status(rec["index"], rec["term"],
                                             commit_abs[g], term_now[g])
                if st == _epoch.COMPLETE:
                    del self._records[(g, req)]
                elif st == _epoch.INVALIDATED:
                    rec["index"] = -1
                    rec["retry"] = self.cluster.step_index
                elif rec["index"] < 0:
                    lead = self.cluster.leader_hint(g)
                    if (lead >= 0 and self.cluster.step_index
                            > rec["retry"] + _epoch.RETRY_STEPS):
                        rec["retry"] = self.cluster.step_index
                        self.cluster.submit(g, lead, rec["payload"],
                                            conn=self._conn(g, req),
                                            req_id=req)

    def drive(self) -> None:
        """One transition pass, on the stepping thread with the
        dispatch pipeline drained (``_drain_admin``). Enumerate →
        diff → seed deltas; converged ⟹ freeze; frozen + converged +
        verified + quiet ⟹ cutover. Defers (returns) whenever
        anything is still in flight."""
        with self._lock:
            if self._phase == IDLE:
                return
            with self.cluster._host_lock:
                if self.cluster._tickets:
                    return          # not drained — next iteration
            step = self.cluster.step_index
            if step > self._deadline:
                self._abandon("deadline")
                return
            if self._phase == FROZEN and step > self._freeze_deadline:
                self._abandon("freeze_deadline")
                return
            if self._records:
                return              # seed records still proving
            # repair owns any affected group ⟹ give way (abandon if
            # already frozen: repair's config surgery must not wait
            # out a freeze, and nothing served has moved yet)
            busy = {g for g, _r in self.cluster.need_recovery}
            if busy & self._affected:
                if self._phase == FROZEN:
                    self._abandon("repair")
                return
            enum = self._enumerate()
            if enum is None:
                return      # a group is mid-election — a follower's
                # fold can under-report committed state, so never
                # enumerate (or verify) off one; next pass retries
            expected, actual, affected = enum
            self._affected |= affected
            deltas = self._deltas(expected, actual)
            if deltas:
                self._submit_deltas(deltas)
                return
            if self._phase == SEED:
                # converged as-of-now: freeze the range so the NEXT
                # passes only chase the bounded pre-freeze pipeline
                self._phase = FROZEN
                self._freeze_deadline = step + self.freeze_deadline_steps
                with self._gate_cv:
                    self._frozen_range = (self._rule.lo, self._rule.hi)
                tr = self._tracer()
                if tr is not None and self.window_trace is not None:
                    tr.phase(self.window_trace, "freeze")
                self._trace(obs_trace.TOPOLOGY_FROZEN,
                            direction=self._direction, step=step,
                            deadline=self._freeze_deadline)
                self._metric_set("topology_frozen", 1)
                return
            # FROZEN and zero deltas: every pre-freeze write is
            # copied. Verify digests, then cut over — unless a live
            # txn still holds the commit lane (it finishes within the
            # freeze deadline or we abandon).
            txn = getattr(self.cluster, "txn", None)
            if txn is not None and txn.wants_serial():
                return
            digests = {}
            for t in sorted(set(expected) | set(actual)):
                want = sorted(expected.get(t, {}).items())
                # only what t will SERVE post-cutover counts: a
                # donor's left-behind copies (cand routes them away)
                # are invisible orphans, not a divergence
                have = sorted((k, v)
                              for k, v in actual.get(t, {}).items()
                              if self._cand.group_of(k) == t)
                if want != have:
                    return          # raced — next pass re-diffs
                digests[t] = range_digest(want)
            self._last_digest = digests
            tr = self._tracer()
            if tr is not None and self.window_trace is not None:
                tr.phase(self.window_trace, "verify", once=True)
            self._trace(obs_trace.TOPOLOGY_VERIFIED,
                        direction=self._direction, step=step,
                        digests={str(t): d for t, d in digests.items()})
            self._cutover()

    # ---------------- internals (all hold _lock) ----------------

    def _conn(self, g: int, req: int) -> int:
        """Per-record conn id (the coordinator's stamping recipe, its
        own namespace): unique per (group, req) forever."""
        return self.kvs.conn_for(self.SEED_CLIENT_BASE + req, g)

    # holds-lock: _lock
    def _enumerate(self):
        """Walk every group leader's in-range live pairs. Returns
        ``(expected, actual, affected)``: ``expected[t]`` = the exact
        post-cutover content of target ``t`` in the range (from the
        groups that AUTHORITATIVELY own each key under the live
        router), ``actual[t]`` = what ``t``'s table holds in the range
        today, ``affected`` = every group a key moves from or to."""
        lo, hi = self._rule.lo, self._rule.hi
        live, cand = self.kvs.router, self._cand
        expected: Dict[int, Dict[bytes, bytes]] = {}
        holds: Dict[int, Dict[bytes, bytes]] = {}
        affected = set()
        for g in range(self.G):
            lead = self.cluster.leader_hint(g)
            if lead < 0:
                return None     # leaderless — only a LEADER's fold is
                # guaranteed to cover the full committed frontier
            holds[g] = dict(self.kvs.groups[g].items_in_range(
                lead, lo, hi))
        for g, items in holds.items():
            for k, v in items.items():
                if live.group_of(k) != g:
                    continue        # stale seeded copy, not authority
                dst = cand.group_of(k)
                expected.setdefault(dst, {})[k] = v
                if dst != g:
                    affected.add(g)
                    affected.add(dst)
        # a target's actual range content = its own table walk (native
        # keys + seeded copies); include every group we ever touched
        # so stale copies on emptied targets still get deleted
        actual = {t: {k: v for k, v in holds.get(t, {}).items()}
                  for t in set(expected) | self._affected}
        return expected, actual, affected

    # holds-lock: _lock
    def _deltas(self, expected, actual) -> List[Tuple[int, str, bytes, bytes]]:
        """``(group, kind, key, val)`` records that make every
        target's range content equal its expected post-cutover
        content. Only targets are written — donors are never touched
        before cutover."""
        out: List[Tuple[int, str, bytes, bytes]] = []
        for t in set(expected) | set(actual):
            want = expected.get(t, {})
            have = actual.get(t, {})
            for k, v in want.items():
                if have.get(k) != v and self.kvs.router.group_of(k) != t:
                    out.append((t, "put", k, v))
            for k in have:
                if k not in want and self.kvs.router.group_of(k) != t:
                    out.append((t, "rm", k, b""))
        return out

    # holds-lock: _lock
    def _submit_deltas(self, deltas) -> None:
        first = not self.transitions_total and not self._last_digest
        n = 0
        for g, kind, k, v in deltas:
            self._req[g] += 1
            req = self._req[g]
            payload = encode_cmd(
                OP_PUT if kind == "put" else OP_RM, k, v
            ).astype("<i4").tobytes()
            self._records[(g, req)] = dict(
                kind=kind, key=k, payload=payload, index=-1, term=0,
                retry=self.cluster.step_index)
            self._terms.reset(g)
            lead = self.cluster.leader_hint(g)
            self.cluster.submit(g, lead if lead >= 0 else 0, payload,
                                conn=self._conn(g, req), req_id=req)
            n += 1
        tr = self._tracer()
        if tr is not None and self.window_trace is not None:
            # once=True: the FIRST seed pass marks the phase; catch-up
            # passes annotate cumulative record counts instead
            tr.phase(self.window_trace, "seed", once=True)
        self._trace(obs_trace.TOPOLOGY_SEEDED,
                    direction=self._direction, records=n,
                    step=self.cluster.step_index, initial=first)
        self._metric_inc("topology_seed_records_total", n)

    # holds-lock: _lock
    def _cutover(self) -> None:
        """The atomic swap, on the stepping thread with dispatches
        drained. Order is load-bearing and trace-proven: leases
        revoked on every affected group BEFORE the router mutates."""
        from rdma_paxos_tpu.runtime.sim import require_drained
        with self.cluster._host_lock:
            require_drained(self.cluster._tickets, "topology_cutover")
        step = self.cluster.step_index
        leases = getattr(self.cluster, "leases", None)
        if leases is not None:
            for g in sorted(self._affected):
                leases.revoke_any(g, "topology_cutover")
        if self._direction == "split":
            version = self.kvs.router.install_rule(self._rule)
        else:
            version = self.kvs.router.remove_rule(self._rule)
        ep = self.epoch.bump()
        donors = sorted(self._affected - {self._rule.group}) \
            if self._direction == "split" else [self._rule.group]
        targets = sorted(self._affected - set(donors))
        tr = self._tracer()
        if tr is not None and self.window_trace is not None:
            tr.phase(self.window_trace, "cutover")
            tr.annotate(self.window_trace, epoch=ep,
                        router_version=version, donors=donors,
                        targets=targets)
        self._trace(obs_trace.TOPOLOGY_CUTOVER,
                    direction=self._direction, step=step, epoch=ep,
                    router_version=version, donors=donors,
                    targets=targets)
        self.transitions_total += 1
        self._metric_inc("topology_transitions_total",
                         direction=self._direction)
        self._metric_set("topology_epoch", ep)
        # driver hook: fail donor in-flight waiters (their entries may
        # commit in a group the new routing no longer serves for these
        # keys) and unpin their conns so retries re-route
        hook = getattr(self.cluster, "_on_topology_cutover", None)
        if hook is not None:
            hook(donors, targets)
        self._close(done=True)

    # holds-lock: _lock
    def _abandon(self, reason: str) -> None:
        self.abandoned_total += 1
        tr = self._tracer()
        if tr is not None and self.window_trace is not None:
            tr.annotate(self.window_trace, reason=reason)
        self._trace(obs_trace.TOPOLOGY_ABANDONED,
                    direction=self._direction, reason=reason,
                    step=self.cluster.step_index)
        self._metric_inc("topology_abandoned_total", reason=reason)
        self._close(done=False)

    # holds-lock: _lock
    def _close(self, *, done: bool) -> None:
        with self._gate_cv:
            self._frozen_range = None
            self._gate_cv.notify_all()
        self._metric_set("topology_frozen", 0)
        if done:
            self._trace(obs_trace.TOPOLOGY_DONE,
                        direction=self._direction,
                        step=self.cluster.step_index,
                        epoch=self.epoch.current())
        tr = self._tracer()
        if tr is not None and self.window_trace is not None:
            tr.end(self.window_trace,
                   status=("done" if done else "abandoned"))
        if self.window_trace is not None:
            # pointer swap, still under _lock: an in-flight TOPOLOGY
            # abort races the close and must still find the window it
            # was aborted by (coordinator falls back to this one)
            self.last_window_trace = self.window_trace
            self.window_trace = None
        self._phase = IDLE
        self._direction = None
        self._rule = None
        self._cand = None
        self._records.clear()
        self._affected = set()
        self._cooldown_until = (self.cluster.step_index
                                + self.cooldown_steps)

    # ---------------- export ----------------

    def status(self) -> dict:
        with self._lock:
            rule = self._rule
            out = dict(
                phase=self._phase,
                direction=self._direction,
                rule=(rule.to_dict() if rule is not None else None),
                epoch=self.epoch.current(),
                router_version=self.kvs.router.version,
                frozen=self.frozen(),
                records_outstanding=len(self._records),
                affected=sorted(self._affected),
                transitions_total=self.transitions_total,
                abandoned_total=self.abandoned_total,
                cooldown_until=self._cooldown_until,
                deadline=self._deadline,
                digests={str(t): d
                         for t, d in self._last_digest.items()},
            )
        # policy status OUTSIDE the controller lock (the policy lock
        # is outermost — taking it under ours would invert the order)
        pol = self.policy
        out["policy"] = pol.status() if pol is not None else None
        return out

    def _trace(self, kind: str, **fields) -> None:
        if self.obs is not None:
            self.obs.trace.record(kind, **fields)

    def _metric_inc(self, name: str, n: int = 1, **labels) -> None:
        if self.obs is not None:
            self.obs.metrics.inc(name, n, **labels)

    def _metric_set(self, name: str, v, **labels) -> None:
        if self.obs is not None:
            self.obs.metrics.set(name, v, **labels)
