"""Replica mesh + the two execution modes of the protocol step.

The reference's distribution fabric is one RC QP pair per peer over
InfiniBand (``src/dare/dare_ibv_rc.c``). The TPU equivalent is a 1-D
``jax.sharding.Mesh`` over the ``replica`` axis — one consensus replica per
chip — with the protocol step compiled via ``shard_map`` so XLA lowers the
gathers onto ICI.

Because the step is written against an *axis name* (``lax.axis_index`` /
``lax.all_gather``), the identical protocol code also runs under
``jax.vmap(..., axis_name=REPLICA_AXIS)``: N replicas simulated on a single
chip (or CPU) with real collective semantics. That is the deterministic
multi-replica test harness the reference never had (SURVEY.md §4) and the
single-chip benchmarking mode.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.state import ReplicaState, make_replica_state
from rdma_paxos_tpu.consensus.step import StepInput, replica_step

REPLICA_AXIS = "replica"
GROUP_AXIS = "group"


def _shard_map(f, *, mesh: Mesh, in_specs, out_specs):
    """Version-portable shard_map: ``jax.shard_map`` (with its
    ``check_vma`` knob) on new JAX, ``jax.experimental.shard_map``
    (``check_rep``) on older installs — same semantics, replication
    checking off in both (the step's outputs are per-replica by
    construction)."""
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map as sm
        return sm(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_rep=False)


def make_replica_mesh(n_replicas: int,
                      devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh with one consensus replica per device."""
    devs = list(jax.devices() if devices is None else devices)[:n_replicas]
    if len(devs) < n_replicas:
        raise ValueError(
            f"need {n_replicas} devices, have {len(devs)}")
    import numpy as np
    return Mesh(np.array(devs), (REPLICA_AXIS,))


def build_mesh_2d(group_shards: int, replicas: int,
                  devices: Optional[Sequence] = None) -> Mesh:
    """2-D device mesh ``(group, replica)`` — the multi-chip layout of
    the sharded cluster. Groups are sharded across the ``group`` device
    axis (each device row owns ``G / group_shards`` whole groups);
    every replica-axis collective of the protocol step (the quorum
    gathers / psum fan-out) is named on the ``replica`` axis, so no
    collective ever crosses the group axis — the ICI traffic of G
    groups is G *independent* R-chip rings, exactly the fault/perf
    isolation the host layer assumes. Uses ``group_shards * replicas``
    devices."""
    need = int(group_shards) * int(replicas)
    devs = list(jax.devices() if devices is None else devices)
    if len(devs) < need:
        raise ValueError(
            f"need {need} devices for a {group_shards}x{replicas} "
            f"mesh, have {len(devs)}")
    import numpy as np
    return Mesh(np.array(devs[:need]).reshape(group_shards, replicas),
                (GROUP_AXIS, REPLICA_AXIS))


def group_sharding(mesh: Mesh):
    """The ``NamedSharding`` placing ``[group, replica, ...]`` state
    pytrees on a :func:`build_mesh_2d` mesh."""
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, P(GROUP_AXIS, REPLICA_AXIS))


def stack_states(cfg: LogConfig, n_replicas: int, group_size: int
                 ) -> ReplicaState:
    """Batched initial state: every leaf gains a leading replica axis."""
    one = make_replica_state(cfg, group_size, n_replicas)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n_replicas,) + x.shape), one)


def stack_group_states(cfg: LogConfig, n_groups: int, n_replicas: int,
                       group_size: int) -> ReplicaState:
    """Batched initial state for a sharded multi-group cluster: every
    leaf gains leading ``[group, replica]`` axes. All G groups start
    from the identical per-replica state — divergence comes only from
    per-group inputs (timeouts, batches, masks)."""
    one = stack_states(cfg, n_replicas, group_size)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n_groups,) + x.shape), one)


def _squeeze(tree):
    return jax.tree.map(lambda x: x[0], tree)


def _unsqueeze(tree):
    return jax.tree.map(lambda x: x[None], tree)


def build_spmd_step(cfg: LogConfig, n_replicas: int, mesh: Mesh, *,
                    use_pallas: bool = False, interpret: bool = False,
                    donate: bool = True, fanout: str = "gather",
                    elections: bool = True, audit: bool = False,
                    telemetry: bool = False, txn: bool = False):
    """Compile the protocol step over a real device mesh.

    Takes/returns *batched* pytrees (leading ``replica`` axis, sharded one
    row per device). State buffers are donated so the log arrays update
    in-place on device across steps — the analog of the reference's log
    living pinned in registered MRs (``rc_memory_reg``,
    ``dare_ibv_rc.c:240-276``).
    """
    core = functools.partial(
        replica_step, cfg=cfg, n_replicas=n_replicas,
        axis_name=REPLICA_AXIS, use_pallas=use_pallas, interpret=interpret,
        fanout=fanout, elections=elections, audit=audit,
        telemetry=telemetry, txn=txn)

    def per_device(state_b, inp_b):
        st, out = core(_squeeze(state_b), _squeeze(inp_b))
        return _unsqueeze(st), _unsqueeze(out)

    mapped = _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(REPLICA_AXIS), P(REPLICA_AXIS)),
        out_specs=(P(REPLICA_AXIS), P(REPLICA_AXIS)))
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def build_sim_burst(cfg: LogConfig, n_replicas: int, *,
                    use_pallas: bool = False, interpret: bool = False,
                    donate: bool = True, fanout: str = "gather",
                    audit: bool = False,
                    telemetry: bool = False):
    """K protocol steps fused into ONE dispatch (``lax.scan``) over the
    vmapped axis — the multi-step driver mode that amortizes host dispatch
    overhead when the submit queue is deep (the analog of the reference's
    busy commit loop staying on the NIC for many iterations per poll,
    ``rc_write_remote_logs`` ``dare_ibv_rc.c:1870-1948``).

    No elections fire inside a burst (timeouts forced 0; every scan step
    carries the leader heartbeat), so the burst compiles the STABLE step
    (``elections=False`` — Phase B could only ever be a no-op; statically
    removing it drops one collective per scan step). The host apply
    cursors are frozen across the burst (the host cannot replay
    mid-burst), so pruning advances at most to the pre-burst applied
    offsets; the caller's capacity sizing must fit the whole burst in
    the pre-burst free space. K is the leading axis of the stacked
    inputs; returns the final state plus per-step stacked outputs for
    exact host accounting."""
    import jax.numpy as jnp
    from jax import lax

    core = functools.partial(
        replica_step, cfg=cfg, n_replicas=n_replicas,
        axis_name=REPLICA_AXIS, use_pallas=use_pallas, interpret=interpret,
        fanout=fanout, elections=False, audit=audit,
        telemetry=telemetry)
    vstep = jax.vmap(core, in_axes=(0, 0), axis_name=REPLICA_AXIS)

    def burst(state_b, datas, metas, counts, peer_mask, applied, qdepth):
        # NOTE: created in-trace, NOT closure-captured — a captured jnp
        # array becomes a lifted executable constant, and on the
        # tunneled TPU backend any program carrying lifted constants
        # pays a flat ~100 ms per dispatch (measured round 5; it was
        # round 4's entire "dispatch floor")
        zeros_r = jnp.zeros((n_replicas,), jnp.int32)
        # datas [K, R, B, sw]; metas [K, R, B, MW]; counts [K, R];
        # applied [R] = the HOST's true apply cursors, frozen across the
        # burst — echoing st.commit here would let pressure-gated (and
        # forced) pruning recycle slots the host has not replayed yet.
        # qdepth [R] = the host backlog REMAINING beyond this burst, so
        # the final step's gathered burst_hint keeps bursts back-to-back
        # under sustained load instead of resetting to zero
        def body(st, xs):
            d, m, c = xs
            inp = StepInput(
                batch_data=d, batch_meta=m, batch_count=c,
                timeout_fired=zeros_r, peer_mask=peer_mask,
                apply_done=applied, queue_depth=qdepth)
            st, out = vstep(st, inp)
            return st, out
        return lax.scan(body, state_b, (datas, metas, counts))
    return jax.jit(burst, donate_argnums=(0,) if donate else ())


def build_sim_scan(cfg: LogConfig, n_replicas: int, *,
                   replay_slots: int,
                   use_pallas: bool = False, interpret: bool = False,
                   donate: bool = True, fanout: str = "gather",
                   audit: bool = False, telemetry: bool = False):
    """The device-resident K-window scan tier: K fused protocol steps
    (the :func:`build_sim_burst` ``lax.scan``) returning ONE
    consolidated minimal readback instead of the full per-step output
    stacks — only what the host rules consume:

    * ``scal`` ``[K, R, len(SCAN_KEYS)]`` i32 — the per-step scalar
      matrix (``accepted`` cumulative; the host reads row ``[-1]``),
    * ``peer_acked`` ``[K, R, R]`` — the failure detector's input,
    * ``replay_data``/``replay_meta`` — ``replay_slots`` committed
      rows per replica starting at the host's PRE-scan apply cursors,
      extracted from the post-scan log INSIDE the same dispatch, so
      the host's replay sweep needs no separate fetch dispatch,
    * per-step audit windows / telemetry vectors, only when those
      variants are compiled (the ``audit=``/``telemetry=`` guard
      discipline — default programs carry neither).

    The protocol computation is exactly the burst's (stable step,
    same inputs, same donation), so scan outputs are bit-identical to
    K serial steps — pinned by ``tests/test_scan.py``. Engines cache
    the compiled fn under distinct ``"scan"``-marked STEP_CACHE keys:
    scan-off clusters' key sets and programs are untouched."""
    import jax.numpy as jnp
    from jax import lax
    from rdma_paxos_tpu.consensus.log import extract_window
    from rdma_paxos_tpu.consensus.step import scan_readback

    core = functools.partial(
        replica_step, cfg=cfg, n_replicas=n_replicas,
        axis_name=REPLICA_AXIS, use_pallas=use_pallas,
        interpret=interpret, fanout=fanout, elections=False,
        audit=audit, telemetry=telemetry)
    vstep = jax.vmap(core, in_axes=(0, 0), axis_name=REPLICA_AXIS)
    vfetch = jax.vmap(lambda log, s: extract_window(
        log, s, replay_slots))

    def scan(state_b, datas, metas, counts, peer_mask, applied,
             qdepth):
        zeros_r = jnp.zeros((n_replicas,), jnp.int32)

        def body(carry, xs):
            st, acc = carry
            d, m, c = xs
            inp = StepInput(
                batch_data=d, batch_meta=m, batch_count=c,
                timeout_fired=zeros_r, peer_mask=peer_mask,
                apply_done=applied, queue_depth=qdepth)
            st, out = vstep(st, inp)
            acc = acc + out.accepted
            ys = scan_readback(out, acc, audit=audit,
                               telemetry=telemetry)
            return (st, acc), ys

        (st, _acc), ys = lax.scan(body, (state_b, zeros_r),
                                  (datas, metas, counts))
        wd, wm = vfetch(st.log, applied)
        ys["replay_data"] = wd
        ys["replay_meta"] = wm
        return st, ys
    return jax.jit(scan, donate_argnums=(0,) if donate else ())


def build_sim_group_scan(cfg: LogConfig, n_replicas: int, *,
                         replay_slots: int,
                         use_pallas: bool = False,
                         interpret: bool = False,
                         donate: bool = True, fanout: str = "gather",
                         audit: bool = False,
                         telemetry: bool = False):
    """:func:`build_sim_scan` with a leading ``group`` batch axis —
    the sharded engine's K-window scan tier (inputs shaped like
    :func:`build_sim_group_burst`; readback dict axes gain ``G``)."""
    import jax.numpy as jnp
    from jax import lax
    from rdma_paxos_tpu.consensus.log import extract_window
    from rdma_paxos_tpu.consensus.step import group_step, scan_readback

    gstep = group_step(cfg=cfg, n_replicas=n_replicas,
                       axis_name=REPLICA_AXIS, use_pallas=use_pallas,
                       interpret=interpret, fanout=fanout,
                       elections=False, audit=audit,
                       telemetry=telemetry)
    vfetch = jax.vmap(jax.vmap(lambda log, s: extract_window(
        log, s, replay_slots)))

    def scan(state_gb, datas, metas, counts, peer_mask, applied,
             qdepth):
        zeros_gr = jnp.zeros_like(counts[0])

        def body(carry, xs):
            st, acc = carry
            d, m, c = xs
            inp = StepInput(
                batch_data=d, batch_meta=m, batch_count=c,
                timeout_fired=zeros_gr, peer_mask=peer_mask,
                apply_done=applied, queue_depth=qdepth)
            st, out = gstep(st, inp)
            acc = acc + out.accepted
            ys = scan_readback(out, acc, audit=audit,
                               telemetry=telemetry)
            return (st, acc), ys

        (st, _acc), ys = lax.scan(body, (state_gb, zeros_gr),
                                  (datas, metas, counts))
        wd, wm = vfetch(st.log, applied)
        ys["replay_data"] = wd
        ys["replay_meta"] = wm
        return st, ys
    return jax.jit(scan, donate_argnums=(0,) if donate else ())


def build_spmd_group_scan(cfg: LogConfig, n_replicas: int, mesh: Mesh,
                          *, replay_slots: int,
                          use_pallas: bool = False,
                          interpret: bool = False,
                          donate: bool = True, fanout: str = "gather",
                          audit: bool = False,
                          telemetry: bool = False):
    """:func:`build_sim_group_scan` over the 2-D ``(group, replica)``
    mesh: the K-window scan (fused steps + consolidated readback +
    in-dispatch replay-window extraction) compiled via ``shard_map``.
    Each device extracts its own replicas' replay rows locally; the
    out_specs gather assembles the global ``[G, R, ...]`` arrays the
    host bookkeeping expects — same host code as the vmap engine."""
    import jax.numpy as jnp
    from jax import lax
    from rdma_paxos_tpu.consensus.log import Log, extract_window
    from rdma_paxos_tpu.consensus.step import scan_readback

    core = functools.partial(
        replica_step, cfg=cfg, n_replicas=n_replicas,
        axis_name=REPLICA_AXIS, use_pallas=use_pallas,
        interpret=interpret, fanout=fanout, elections=False,
        audit=audit, telemetry=telemetry)
    vcore = jax.vmap(core, in_axes=(0, 0))      # local groups, unnamed

    def per_device(state_b, datas_b, metas_b, counts_b, peer_b,
                   applied_b, qdepth_b):
        st = jax.tree.map(lambda x: x[:, 0], state_b)   # [Gl, ...]
        zeros_g = jnp.zeros_like(counts_b[0, :, 0])     # [Gl]

        def body(carry, xs):
            s, acc = carry
            d, m, c = xs                # d: [Gl, 1, B, sw] etc.
            inp = StepInput(
                batch_data=d[:, 0], batch_meta=m[:, 0],
                batch_count=c[:, 0], timeout_fired=zeros_g,
                peer_mask=peer_b[:, 0], apply_done=applied_b[:, 0],
                queue_depth=qdepth_b[:, 0])
            s, out = vcore(s, inp)
            acc = acc + out.accepted
            ys = scan_readback(out, acc, audit=audit,
                               telemetry=telemetry)
            return (s, acc), ys

        (st, _acc), ys = lax.scan(body, (st, zeros_g),
                                  (datas_b, metas_b, counts_b))
        wd, wm = jax.vmap(lambda buf, s: extract_window(
            Log(buf=buf), s, replay_slots))(st.log.buf,
                                            applied_b[:, 0])
        out = {k: jax.tree.map(lambda x: x[:, :, None], v)
               for k, v in ys.items()}           # [K, Gl, 1, ...]
        out["replay_data"] = wd[:, None]
        out["replay_meta"] = wm[:, None]
        return (jax.tree.map(lambda x: x[:, None], st), out)

    spec_k = P(None, GROUP_AXIS, REPLICA_AXIS)
    out_spec = dict(scal=spec_k, peer_acked=spec_k,
                    replay_data=P(GROUP_AXIS, REPLICA_AXIS),
                    replay_meta=P(GROUP_AXIS, REPLICA_AXIS))
    if audit:
        out_spec.update(audit_start=spec_k, audit_digest=spec_k,
                        audit_term=spec_k, audit_commit=spec_k)
    if telemetry:
        out_spec["telemetry"] = spec_k
    mapped = _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(GROUP_AXIS, REPLICA_AXIS),
                  P(None, GROUP_AXIS, REPLICA_AXIS),
                  P(None, GROUP_AXIS, REPLICA_AXIS),
                  P(None, GROUP_AXIS, REPLICA_AXIS),
                  P(GROUP_AXIS, REPLICA_AXIS),
                  P(GROUP_AXIS, REPLICA_AXIS),
                  P(GROUP_AXIS, REPLICA_AXIS)),
        out_specs=(P(GROUP_AXIS, REPLICA_AXIS), out_spec))
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def build_spmd_scan(cfg: LogConfig, n_replicas: int, mesh: Mesh, *,
                    replay_slots: int,
                    use_pallas: bool = False, interpret: bool = False,
                    donate: bool = True, fanout: str = "psum",
                    audit: bool = False, telemetry: bool = False):
    """:func:`build_sim_scan` over a real 1-D replica mesh — the
    multi-host daemon's K-window scan tier: K fused steps + the
    consolidated scalar matrix + each host's OWN replay window
    extracted from its local log shard inside the one collective
    dispatch (the per-iteration ``fetch_local_window`` dispatches of
    the lock-step loop disappear)."""
    import jax.numpy as jnp
    from jax import lax
    from rdma_paxos_tpu.consensus.log import extract_window
    from rdma_paxos_tpu.consensus.step import scan_readback

    core = functools.partial(
        replica_step, cfg=cfg, n_replicas=n_replicas,
        axis_name=REPLICA_AXIS, use_pallas=use_pallas,
        interpret=interpret, fanout=fanout, elections=False,
        audit=audit, telemetry=telemetry)

    def per_device(state_b, datas_b, metas_b, counts_b, peer_b,
                   applied_b, qdepth_b):
        st = _squeeze(state_b)

        def body(carry, xs):
            s, acc = carry
            d, m, c = xs
            inp = StepInput(
                batch_data=d[0], batch_meta=m[0], batch_count=c[0],
                timeout_fired=jnp.zeros((), jnp.int32),
                peer_mask=peer_b[0], apply_done=applied_b[0],
                queue_depth=qdepth_b[0])
            s, out = core(s, inp)
            acc = acc + out.accepted
            ys = scan_readback(out, acc, audit=audit,
                               telemetry=telemetry)
            return (s, acc), ys

        (st, _acc), ys = lax.scan(
            body, (st, jnp.zeros((), jnp.int32)),
            (datas_b, metas_b, counts_b))
        wd, wm = extract_window(st.log, applied_b[0], replay_slots)
        out = {k: jax.tree.map(lambda x: x[:, None], v)
               for k, v in ys.items()}           # [K, 1, ...]
        out["replay_data"] = wd[None]
        out["replay_meta"] = wm[None]
        return _unsqueeze(st), out

    spec_k = P(None, REPLICA_AXIS)
    out_spec = dict(scal=spec_k, peer_acked=spec_k,
                    replay_data=P(REPLICA_AXIS),
                    replay_meta=P(REPLICA_AXIS))
    if audit:
        out_spec.update(audit_start=spec_k, audit_digest=spec_k,
                        audit_term=spec_k, audit_commit=spec_k)
    if telemetry:
        out_spec["telemetry"] = spec_k
    mapped = _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(REPLICA_AXIS), P(None, REPLICA_AXIS),
                  P(None, REPLICA_AXIS), P(None, REPLICA_AXIS),
                  P(REPLICA_AXIS), P(REPLICA_AXIS), P(REPLICA_AXIS)),
        out_specs=(P(REPLICA_AXIS), out_spec))
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def build_spmd_burst(cfg: LogConfig, n_replicas: int, mesh: Mesh, *,
                     use_pallas: bool = False, interpret: bool = False,
                     donate: bool = True, fanout: str = "gather",
                     audit: bool = False,
                     telemetry: bool = False):
    """:func:`build_sim_burst` over a real device mesh (``shard_map`` with
    the K-step scan inside the per-device program)."""
    import jax.numpy as jnp
    from jax import lax

    core = functools.partial(
        replica_step, cfg=cfg, n_replicas=n_replicas,
        axis_name=REPLICA_AXIS, use_pallas=use_pallas, interpret=interpret,
        fanout=fanout, elections=False, audit=audit,
        telemetry=telemetry)

    def per_device(state_b, datas_b, metas_b, counts_b, peer_b,
                   applied_b, qdepth_b):
        st = _squeeze(state_b)

        def body(s, xs):
            d, m, c = xs
            inp = StepInput(
                batch_data=d[0], batch_meta=m[0], batch_count=c[0],
                timeout_fired=jnp.zeros((), jnp.int32),
                peer_mask=peer_b[0], apply_done=applied_b[0],
                # remaining backlog rides every burst step's gather so
                # the final burst_hint sustains back-to-back bursts
                queue_depth=qdepth_b[0])
            s, out = core(s, inp)
            return s, out
        st, outs = lax.scan(body, st, (datas_b, metas_b, counts_b))
        return (_unsqueeze(st),
                jax.tree.map(lambda x: x[:, None], outs))   # [K, 1, ...]

    mapped = _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(REPLICA_AXIS), P(None, REPLICA_AXIS),
                  P(None, REPLICA_AXIS), P(None, REPLICA_AXIS),
                  P(REPLICA_AXIS), P(REPLICA_AXIS), P(REPLICA_AXIS)),
        out_specs=(P(REPLICA_AXIS), P(None, REPLICA_AXIS)))
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def build_sim_group_step(cfg: LogConfig, n_replicas: int, *,
                         use_pallas: bool = False, interpret: bool = False,
                         donate: bool = True, fanout: str = "gather",
                         elections: bool = True, audit: bool = False,
                         telemetry: bool = False, txn: bool = False):
    """Compile the G-group × R-replica protocol step as ONE program on
    one device (:func:`rdma_paxos_tpu.consensus.step.group_step` under
    ``jit``). The group axis is an unnamed batch axis — groups are
    independent; only the replica axis carries collectives — so one
    dispatch steps every group (the sharded-cluster hot path)."""
    from rdma_paxos_tpu.consensus.step import group_step
    mapped = group_step(cfg=cfg, n_replicas=n_replicas,
                        axis_name=REPLICA_AXIS, use_pallas=use_pallas,
                        interpret=interpret, fanout=fanout,
                        elections=elections, audit=audit,
                        telemetry=telemetry, txn=txn)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def build_sim_group_burst(cfg: LogConfig, n_replicas: int, *,
                          use_pallas: bool = False,
                          interpret: bool = False,
                          donate: bool = True, fanout: str = "gather",
                          audit: bool = False,
                          telemetry: bool = False):
    """:func:`build_sim_burst` with a leading ``group`` batch axis: K
    fused protocol steps over ALL G groups in ONE dispatch
    (``lax.scan`` of the group-batched stable step). Same contract as
    the single-group burst — no elections inside the burst, host apply
    cursors frozen across it, capacity sized by the caller — applied
    per group. Inputs: datas ``[K, G, R, B, sw]``, metas
    ``[K, G, R, B, MW]``, counts ``[K, G, R]``, peer_mask
    ``[G, R, R]``, applied/qdepth ``[G, R]``."""
    import jax.numpy as jnp
    from jax import lax
    from rdma_paxos_tpu.consensus.step import group_step

    gstep = group_step(cfg=cfg, n_replicas=n_replicas,
                       axis_name=REPLICA_AXIS, use_pallas=use_pallas,
                       interpret=interpret, fanout=fanout,
                       elections=False, audit=audit,
                       telemetry=telemetry)

    def burst(state_gb, datas, metas, counts, peer_mask, applied, qdepth):
        zeros_gr = jnp.zeros_like(counts[0])

        def body(st, xs):
            d, m, c = xs
            inp = StepInput(
                batch_data=d, batch_meta=m, batch_count=c,
                timeout_fired=zeros_gr, peer_mask=peer_mask,
                apply_done=applied, queue_depth=qdepth)
            return gstep(st, inp)
        return lax.scan(body, state_gb, (datas, metas, counts))
    return jax.jit(burst, donate_argnums=(0,) if donate else ())


def build_spmd_group_step(cfg: LogConfig, n_replicas: int, mesh: Mesh,
                          *, use_pallas: bool = False,
                          interpret: bool = False, donate: bool = True,
                          fanout: str = "gather",
                          elections: bool = True, audit: bool = False,
                          telemetry: bool = False, txn: bool = False):
    """:func:`build_sim_group_step` over a REAL 2-D ``(group,
    replica)`` device mesh (:func:`build_mesh_2d`): G groups × R
    replicas advanced by ONE ``shard_map``-compiled dispatch spanning
    ``group_shards * R`` chips.

    Axis layout: the global ``[G, R, ...]`` pytrees are sharded
    ``P(group, replica)`` — each device holds ``G / group_shards``
    whole group rows of exactly one replica column. Inside the
    per-device program the replica axis (local size 1) is squeezed and
    the local group rows ride an *unnamed* ``vmap``, so every
    collective in :func:`replica_step` binds the ``replica`` MESH axis
    only: quorum traffic crosses the R chips of one replica ring,
    never the group axis. The compiled program is polymorphic in the
    local group count, so the cache key carries the mesh — not G
    (``tests/test_mesh.py`` pins the single-compile property)."""
    core = functools.partial(
        replica_step, cfg=cfg, n_replicas=n_replicas,
        axis_name=REPLICA_AXIS, use_pallas=use_pallas,
        interpret=interpret, fanout=fanout, elections=elections,
        audit=audit,
        telemetry=telemetry, txn=txn)
    vcore = jax.vmap(core, in_axes=(0, 0))      # local groups, unnamed

    def per_device(state_b, inp_b):
        st, out = vcore(jax.tree.map(lambda x: x[:, 0], state_b),
                        jax.tree.map(lambda x: x[:, 0], inp_b))
        return (jax.tree.map(lambda x: x[:, None], st),
                jax.tree.map(lambda x: x[:, None], out))

    mapped = _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(GROUP_AXIS, REPLICA_AXIS),
                  P(GROUP_AXIS, REPLICA_AXIS)),
        out_specs=(P(GROUP_AXIS, REPLICA_AXIS),
                   P(GROUP_AXIS, REPLICA_AXIS)))
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def build_spmd_group_burst(cfg: LogConfig, n_replicas: int, mesh: Mesh,
                           *, use_pallas: bool = False,
                           interpret: bool = False,
                           donate: bool = True, fanout: str = "gather",
                           audit: bool = False,
                           telemetry: bool = False):
    """:func:`build_sim_group_burst` over the 2-D ``(group, replica)``
    mesh: K fused protocol steps × ALL G groups in ONE multi-chip
    dispatch (``lax.scan`` of the group-vmapped stable step inside the
    per-device program). Same contract as the single-device group
    burst — no elections inside, host apply cursors frozen, capacity
    sized by the caller — applied per group. Input shapes match
    :func:`build_sim_group_burst`; K is unsharded, ``[G, R]`` axes are
    sharded ``P(group, replica)``."""
    import jax.numpy as jnp
    from jax import lax

    core = functools.partial(
        replica_step, cfg=cfg, n_replicas=n_replicas,
        axis_name=REPLICA_AXIS, use_pallas=use_pallas,
        interpret=interpret, fanout=fanout, elections=False,
        audit=audit,
        telemetry=telemetry)
    vcore = jax.vmap(core, in_axes=(0, 0))      # local groups, unnamed

    def per_device(state_b, datas_b, metas_b, counts_b, peer_b,
                   applied_b, qdepth_b):
        st = jax.tree.map(lambda x: x[:, 0], state_b)   # [Gl, ...]
        zeros_g = jnp.zeros_like(counts_b[0, :, 0])     # [Gl]

        def body(s, xs):
            d, m, c = xs                # d: [Gl, 1, B, sw] etc.
            inp = StepInput(
                batch_data=d[:, 0], batch_meta=m[:, 0],
                batch_count=c[:, 0], timeout_fired=zeros_g,
                peer_mask=peer_b[:, 0], apply_done=applied_b[:, 0],
                queue_depth=qdepth_b[:, 0])
            return vcore(s, inp)
        st, outs = lax.scan(body, st, (datas_b, metas_b, counts_b))
        return (jax.tree.map(lambda x: x[:, None], st),
                jax.tree.map(lambda x: x[:, :, None], outs))

    mapped = _shard_map(
        per_device, mesh=mesh,
        in_specs=(P(GROUP_AXIS, REPLICA_AXIS),
                  P(None, GROUP_AXIS, REPLICA_AXIS),
                  P(None, GROUP_AXIS, REPLICA_AXIS),
                  P(None, GROUP_AXIS, REPLICA_AXIS),
                  P(GROUP_AXIS, REPLICA_AXIS),
                  P(GROUP_AXIS, REPLICA_AXIS),
                  P(GROUP_AXIS, REPLICA_AXIS)),
        out_specs=(P(GROUP_AXIS, REPLICA_AXIS),
                   P(None, GROUP_AXIS, REPLICA_AXIS)))
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def build_sim_step(cfg: LogConfig, n_replicas: int, *,
                   use_pallas: bool = False, interpret: bool = False,
                   donate: bool = True, fanout: str = "gather",
                   elections: bool = True, audit: bool = False,
                   telemetry: bool = False, txn: bool = False):
    """Compile the protocol step as an N-replica simulation on one device
    (``vmap`` with a named axis — identical collective semantics)."""
    core = functools.partial(
        replica_step, cfg=cfg, n_replicas=n_replicas,
        axis_name=REPLICA_AXIS, use_pallas=use_pallas, interpret=interpret,
        fanout=fanout, elections=elections, audit=audit,
        telemetry=telemetry, txn=txn)
    mapped = jax.vmap(core, in_axes=(0, 0), axis_name=REPLICA_AXIS)
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())
