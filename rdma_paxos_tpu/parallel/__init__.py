from rdma_paxos_tpu.parallel.mesh import (  # noqa: F401
    REPLICA_AXIS,
    make_replica_mesh,
    build_spmd_step,
    build_sim_step,
    stack_states,
)
