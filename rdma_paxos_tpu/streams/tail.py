"""Tail-follower core — positioned record reads over the committed
replay streams.

The engines already replay every committed client entry into per
replica ``LazyReplayStream``s (runtime/hostpath.py): an ordered,
append-only, prefix-identical-across-replicas event stream. This
module opens that stream as a consumable product: a
:class:`GroupTail` snapshots one group's stream under the engine host
lock and decodes it into :class:`Record`s carrying the log's OWN
coordinates — ``(term, absolute index)`` from the decode-time meta
columns (``ReplayBatch.terms``/``gidx``) — plus the stream POSITION,
which is stable across leader failover and i32 rebases (the committed
prefix never shrinks and rebase renumbers slots, not stream entries).

All three serving surfaces (scan cuts, watch resume tokens, CDC
records) are built on these two coordinate systems: positions anchor
host-side cursors and consistent cuts; ``(term, index)`` names the
same entry in the AuditLedger's coordinates for cross-host and
cross-artifact verification.

Host-pure: this module must never reach into the accelerator stack
(enforced by the analysis ``host-purity`` pass).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from rdma_paxos_tpu.consensus.log import EntryType

# KVS command byte layout — pinned to the state machine's codec
# (models/kvs.py: CMD_W = 1 + KEY_W + VAL_W i32 words). Redeclared
# here so the host-pure streams plane never imports the device
# state-machine module; tests/test_streams.py pins the equality.
KEY_BYTES = 32
VAL_BYTES = 32
CMD_BYTES = 4 + KEY_BYTES + VAL_BYTES
OP_PUT, OP_GET, OP_RM = 1, 2, 3

_SEND = int(EntryType.SEND)


def decode_kvs(payload: bytes) -> Optional[Tuple[int, bytes, bytes]]:
    """``(op, key, val)`` of a KVS command payload, or None when the
    payload is not one (wrong size — the same length gate the apply
    fold uses). Key/value unpadding mirrors ``models.kvs.decode_val``
    (trailing NULs stripped)."""
    if len(payload) != CMD_BYTES:
        return None
    op = int.from_bytes(payload[0:4], "little", signed=True)
    key = payload[4:4 + KEY_BYTES].rstrip(b"\x00")
    val = payload[4 + KEY_BYTES:CMD_BYTES].rstrip(b"\x00")
    return op, key, val


class Record:
    """One committed client entry with its log coordinates. ``term``
    and ``index`` are -1 for entries whose batch coordinates were lost
    to a legacy tuple materialization (cold paths only — the live
    decode always carries them)."""

    __slots__ = ("group", "term", "index", "etype", "conn", "req",
                 "payload", "pos")

    def __init__(self, group: int, term: int, index: int, etype: int,
                 conn: int, req: int, payload: bytes, pos: int):
        self.group = group
        self.term = term
        self.index = index      # absolute log index (rebase-corrected)
        self.etype = etype
        self.conn = conn
        self.req = req
        self.payload = payload
        self.pos = pos          # stream position (failover-stable)

    def __repr__(self) -> str:
        return (f"Record(g={self.group} t={self.term} i={self.index} "
                f"e={self.etype} c={self.conn} q={self.req} "
                f"pos={self.pos})")


class DedupFold:
    """The app fold's exactly-once acceptance rule, mirrored for
    stream consumers (``ReplicatedKVS._fold``): only SEND entries of
    command size count; stamped entries (``conn > 0 and req > 0``)
    are accepted once per ``(conn, req)`` high-water mark — a
    retransmitted duplicate occupying a later log slot is skipped
    exactly like the app skips it."""

    def __init__(self):
        self.last_req = {}
        self.deduped = 0

    def accept(self, rec: Record) -> bool:
        if rec.etype != _SEND or len(rec.payload) != CMD_BYTES:
            return False
        if rec.req > 0 and rec.conn > 0:
            if rec.req <= self.last_req.get(rec.conn, 0):
                self.deduped += 1
                return False
            self.last_req[rec.conn] = rec.req
        return True


def _group_streams(cluster, group: int):
    """The per-replica replay streams of ``group`` — the sharded
    engine nests them as ``replayed[g][r]``; SimCluster is flat
    ``[r]`` (branch on engine shape, never on the group count)."""
    rep = cluster.replayed
    if hasattr(cluster, "G"):
        rep = rep[group]
    return rep


class GroupTail:
    """Position-cursor reader over ONE group's committed stream.

    Replicas' streams are prefix-identical (they replay the same
    committed prefix), so positions are replica-independent — the
    tail always reads from whichever replica has applied the most
    (quarantined or lagging replicas simply aren't the longest).
    Snapshots take the engine host lock; decode happens outside it
    (segments are immutable batches plus list-slice copies).
    """

    def __init__(self, cluster, group: int = 0):
        self._cluster = cluster
        self.group = int(group)

    def length(self) -> int:
        """Longest replica stream length — cheap (``__len__`` never
        materializes a lazy stream)."""
        return max((len(s) for s in
                    _group_streams(self._cluster, self.group)),
                   default=0)

    def snapshot(self, lo: int, hi: Optional[int] = None):
        """``(segments, n)`` covering positions ``[lo, min(hi, len))``
        of the longest stream, snapshotted under the engine host lock
        (appends happen under it on the readback thread)."""
        with self._cluster._host_lock:
            streams = _group_streams(self._cluster, self.group)
            best, best_len = None, 0
            for s in streams:
                if len(s) > best_len:
                    best, best_len = s, len(s)
            end = best_len if hi is None else min(int(hi), best_len)
            if best is None or lo >= end:
                return [], 0
            if hasattr(best, "segments_from"):
                segs = best.segments_from(lo)
            else:                       # plain list (tests, recovery)
                segs = [list(best[lo:])]
        return segs, end - lo

    def records(self, lo: int, hi: Optional[int] = None
                ) -> List[Record]:
        """Decode positions ``[lo, hi)`` (``hi`` None = current end)
        into :class:`Record`s."""
        segs, n = self.snapshot(lo, hi)
        out: List[Record] = []
        pos = lo
        g = self.group
        for seg in segs:
            if n <= 0:
                break
            if isinstance(seg, list):
                for etype, conn, req, payload in seg:
                    if n <= 0:
                        break
                    out.append(Record(g, -1, -1, int(etype),
                                      int(conn), int(req), payload,
                                      pos))
                    pos += 1
                    n -= 1
                continue
            t, c, q, o, b = (seg.types, seg.conns, seg.reqs, seg.offs,
                             seg.blob)
            terms, gidx = seg.terms, seg.gidx
            take = min(len(seg), n)
            for i in range(take):
                out.append(Record(
                    g,
                    -1 if terms is None else int(terms[i]),
                    -1 if gidx is None else int(gidx[i]),
                    int(t[i]), int(c[i]), int(q[i]),
                    b[o[i]:o[i + 1]], pos))
                pos += 1
            n -= take
        return out
