"""Log-as-product: the ``streams/`` subsystem.

APUS followers replay the committed input stream into their local app
copies — the log IS an ordered, audited, digest-verified event
stream. This package opens it as a product with three serving
surfaces over one tail-follower core (:mod:`.tail`):

* **ordered range scans** (:mod:`.scan`) — one batched read-index
  confirm per page through the ReadHub, pages served from local
  applied state at the linearization point, with a consistent-cut
  token so pagination never tears across a leader failover;
* **watch/subscribe** (:mod:`.watch`) — committed deltas per key
  range, fanned out from a dedicated pump thread with exactly-once
  resume tokens in audit coordinates ``(group, term, index)``;
* **CDC export** (:mod:`.cdc`) — a JSONL sink carrying the audit
  chain's digests, verifiable end-to-end with
  ``python -m rdma_paxos_tpu.streams verify``.

Entirely host-side: ZERO device changes, ZERO new STEP_CACHE keys
(tests/test_streams.py pins bit-identity attached vs detached), and
pinned host-pure + lock-disciplined by the analysis suite like
``runtime/reads.py`` was.

Wiring: :func:`attach` hangs a :class:`StreamHub` off either engine
(``cluster.streams``); the engines' finish() tail calls
:meth:`StreamHub.observe` after the read drain and before the
governor (a deep watch backlog is demand the governor must see —
``runtime/governor.py`` consults :meth:`StreamHub.backlogs`).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.streams.cdc import CDCWriter
from rdma_paxos_tpu.streams.scan import (
    ScanManager, TokenExpired, groups_for_range, key_range)
from rdma_paxos_tpu.streams.tail import GroupTail
from rdma_paxos_tpu.streams.watch import (
    ResumeExpired, Subscription, WatchHub)

__all__ = [
    "attach", "StreamHub", "ScanFailed", "TokenExpired",
    "ResumeExpired", "Subscription",
]


class ScanFailed(RuntimeError):
    """A scan page's read definitively failed (patience lapsed or the
    engine stopped) — the token stays valid; retry the page."""


def _n_groups(cluster) -> int:
    return cluster.G if hasattr(cluster, "G") else 1


def _leader_of(cluster, group: int) -> int:
    """Highest-term self-claimed leader (the drivers' failover view
    rule), -1 when unknown — engine-shape aware."""
    last = cluster.last
    if last is None:
        return -1
    if hasattr(cluster, "G"):
        return cluster.leader_hint(group)
    claims = [(int(last["term"][r]), r) for r in range(cluster.R)
              if int(last["role"][r]) == int(Role.LEADER)]
    return max(claims)[1] if claims else -1


class StreamHub:
    """The attached subsystem: per-group tails + the three surfaces.
    Client calls (scan/subscribe) are thread-safe; :meth:`observe`
    belongs to the engine finish() tail (readback thread) and is
    O(G) cheap — it never decodes and never blocks on a consumer."""

    def __init__(self, cluster, *, kvs=None, obs=None,
                 cdc_path: Optional[str] = None, auditor=None,
                 queue_cap: int = 1024, retain: int = 1 << 16,
                 pin_steps: int = 512, page_size: int = 64,
                 patience_steps: Optional[int] = None):
        self.cluster = cluster
        self.kvs = kvs
        self.obs = obs if obs is not None else getattr(cluster, "obs",
                                                       None)
        self.page_size = int(page_size)
        self.patience_steps = patience_steps
        self.G = _n_groups(cluster)
        self.tails = [GroupTail(cluster, g) for g in range(self.G)]
        self.cdc = None if cdc_path is None else CDCWriter(
            cdc_path, auditor=auditor, obs=self.obs)
        self.scans = ScanManager(self.tails, pin_steps=pin_steps,
                                 obs=self.obs)
        self.watch = WatchHub(self.tails, obs=self.obs,
                              queue_cap=queue_cap, retain=retain,
                              cdc=self.cdc)
        self._lock = threading.Lock()
        self._hsteps = 0          # guarded-by: _lock
        self._hstopped = False    # guarded-by: _lock
        from rdma_paxos_tpu.analysis import runtime_guard
        runtime_guard.maybe_guard(self, "_lock", __file__)

    # ---------------- engine-side (finish() tail) ----------------

    def observe(self, cluster, res) -> None:
        """Per finished step: note the new committed frontiers, kick
        the pump, tick scan-pin expiry, publish backpressure gauges."""
        lens = {t.group: t.length() for t in self.tails}
        self.watch.kick(lens)
        self.scans.on_step()
        with self._lock:
            self._hsteps += 1
        if self.obs is not None:
            if self.cdc is not None:
                cursors = self.watch.cursors()
                for g, n in lens.items():
                    self.obs.metrics.set("cdc_lag_entries",
                                         max(0, n - cursors.get(g, 0)),
                                         group=g)
            for g, depth in self.watch.backlogs().items():
                self.obs.metrics.set("watch_backlog_entries", depth,
                                     group=g)

    def backlogs(self) -> List[int]:
        """Per-group watch demand for the governor ([G] ints). Never
        takes the engine host lock (the governor calls this right
        after its own host-locked backlog read)."""
        depth = self.watch.backlogs()
        return [depth.get(g, 0) for g in range(self.G)]

    # ---------------- watch ----------------

    def subscribe(self, group: int = 0, *, prefix: bytes = None,
                  lo: bytes = None, hi: bytes = None,
                  token: Optional[dict] = None,
                  cap: Optional[int] = None) -> Subscription:
        rlo, rhi = key_range(prefix, lo, hi)
        return self.watch.subscribe(group, lo=rlo, hi=rhi,
                                    token=token, cap=cap)

    # ---------------- scan ----------------

    def _pick_replica(self, group: int) -> int:
        lm = getattr(self.cluster, "leases", None)
        if lm is not None:
            rep = lm.serving_holder(group)
            if rep is not None and rep >= 0:
                return rep
        rep = _leader_of(self.cluster, group)
        return rep if rep >= 0 else 0

    def scan(self, *, prefix: bytes = None, lo: bytes = None,
             hi: bytes = None, limit: Optional[int] = None,
             token: Optional[dict] = None, group: Optional[int] = None,
             timeout: float = 30.0, retries: int = 3) -> dict:
        """One page of an ordered range scan. Returns ``{items,
        token, done}``: ``items`` is ``[(key, value), ...]`` in key
        order, at most ``limit`` long; pass ``token`` back for the
        next page. The first page pins a consistent cut — every later
        page reads AS OF it, across leader failover (the token holds;
        only pin EXPIRY invalidates it, explicitly).

        Sharded engines fan out per group (router-aware narrowing
        when a range override covers the whole range) and merge-sort
        by key; the token carries per-group cuts."""
        limit = self.page_size if limit is None else int(limit)
        if token is not None:
            rlo = bytes.fromhex(token["lo"])
            rhi = (None if token["hi"] is None
                   else bytes.fromhex(token["hi"]))
            after = (None if token["after"] is None
                     else bytes.fromhex(token["after"]))
            gstate = {int(g): dict(s)
                      for g, s in token["groups"].items()}
        else:
            rlo, rhi = key_range(prefix, lo, hi)
            after = None
            if group is not None:
                groups = [int(group)]
            else:
                router = getattr(self.cluster, "router", None)
                groups = groups_for_range(router, rlo, rhi)
                if groups is None:
                    groups = list(range(self.G))
            gstate = {g: dict(cut=None, done=False) for g in groups}
        reads = getattr(self.cluster, "reads", None)
        if reads is None:
            raise RuntimeError(
                "streams.scan requires the ReadHub (attach reads)")
        pages = {}
        for g, st in gstate.items():
            if st["done"]:
                continue
            pages[g] = self._page_with_retries(
                reads, g, rlo, rhi, after, limit, st["cut"],
                timeout, retries)
        merged = []
        for g, page in pages.items():
            gstate[g]["cut"] = page["cut"]
            gstate[g]["term"] = page["term"]
            gstate[g]["index"] = page["index"]
            if page["done"]:
                gstate[g]["done"] = True
            merged.extend((k, v, g) for k, v in page["items"])
        merged.sort(key=lambda t: t[0])
        emit = merged[:limit]
        items = [(k, v) for k, v, _ in emit]
        leftovers = {g for _, _, g in merged[limit:]}
        for g in leftovers:
            gstate[g]["done"] = False   # re-query past the new after
        done = all(st["done"] for st in gstate.values())
        if done or not items:
            for g, st in gstate.items():
                if st.get("cut") is not None:
                    self.scans.release(g, st["cut"])
            return dict(items=items, token=None, done=True)
        new_after = items[-1][0] if items else after
        out_token = dict(
            v=1, lo=rlo.hex(),
            hi=None if rhi is None else rhi.hex(),
            after=None if new_after is None else new_after.hex(),
            groups={str(g): st for g, st in gstate.items()})
        return dict(items=items, token=out_token, done=False)

    def scan_all(self, **kw) -> List[tuple]:
        """Drain a whole scan (test/tooling convenience)."""
        items: List[tuple] = []
        page = self.scan(**kw)
        while True:
            items.extend(page["items"])
            if page["done"]:
                return items
            page = self.scan(token=page["token"])

    def _page_with_retries(self, reads, group, rlo, rhi, after,
                           limit, cut, timeout, retries) -> dict:
        last_err = "read failed"
        for _ in range(max(1, retries)):
            def serve(t, g=group, c=cut):
                return self.scans.serve_page(
                    g, rlo, rhi, after, limit, c, self.kvs)
            ticket = reads.submit(
                serve, replica=self._pick_replica(group),
                group=group, pass_ticket=True,
                patience=self.patience_steps)
            if not ticket.wait(timeout):
                raise ScanFailed(
                    f"scan page timed out after {timeout}s "
                    f"(group {group})")
            if ticket.status == "ok" and ticket.value is not None:
                page = ticket.value
                if "error" in page:
                    raise TokenExpired(page["error"])
                return page
        raise ScanFailed(
            f"scan page failed (group {group}): {last_err}")

    # ---------------- lifecycle / status ----------------

    def fail_all(self, reason: str) -> None:
        """Driver stop path: stop the pump, close every subscription,
        flush + close the CDC sink. Idempotent."""
        with self._lock:
            if self._hstopped:
                return
            self._hstopped = True
        self.watch.fail_all(reason)
        if self.cdc is not None:
            self.cdc.close()

    def status(self) -> dict:
        with self._lock:
            steps = self._hsteps
            stopped = self._hstopped
        return dict(
            groups=self.G, steps=steps, stopped=stopped,
            watch=self.watch.status(), scan=self.scans.status(),
            cdc=None if self.cdc is None else {
                str(g): self.cdc.exported(g) for g in range(self.G)})


def attach(cluster, **kw) -> StreamHub:
    """Create and wire a :class:`StreamHub` onto ``cluster`` (the
    engines consult ``cluster.streams`` at the finish() tail — same
    attach pattern as ``reads.attach``)."""
    hub = StreamHub(cluster, **kw)
    cluster.streams = hub
    return hub
