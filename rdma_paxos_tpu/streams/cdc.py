"""CDC export — digest-verified JSONL change-data-capture.

A :class:`CDCWriter` drains committed client entries into an external
JSONL sink in the ops plane's concat-mergeable style (one
self-describing record per line; per-host files merge by concat, the
same convention as ``replica<me>.series.jsonl``). Every record
carries:

* the audit chain's coordinates — ``(group, term, absolute index)``;
* the raw entry — etype/conn/req plus the payload hex;
* a running per-group FNV-1a **chain** over the canonical record
  bytes (each link folds the previous link in, so flipping one
  exported byte breaks every later link);
* the AuditLedger's **window digest** for the index, when the ledger
  retains it (the device-side fold covers full slot rows, so an
  exporter cannot recompute it — carrying it ties the export to the
  quorum-compared digest record).

``python -m rdma_paxos_tpu.streams verify EXPORT [AUDIT...]`` proves
an export end-to-end: per-group strictly-increasing indices (client
entries never share a slot; NOOP/CONFIG legitimately occupy the
index gaps), chain recomputation, and — against one or more ledger
dumps — term + digest agreement per retained index. The first bad
record is named by its ``(term, index)`` and the process exits 1.

Host-pure; single-writer by design (the watch pump thread or the
NodeDaemon apply loop), so the only lock is around flush/close.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, List, Optional, Tuple

_FNV_OFF = 2166136261
_FNV_PRIME = 16777619
_MASK = 0xFFFFFFFF


def _fnv1a(data: bytes, h: int = _FNV_OFF) -> int:
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK
    return h


def chain_link(prev: int, group: int, term: int, index: int,
               etype: int, conn: int, req: int,
               payload: bytes) -> int:
    """One chain link: FNV-1a over the previous link plus the
    record's canonical field encoding."""
    head = b"%d|%d|%d|%d|%d|%d|" % (group, term, index, etype, conn,
                                    req)
    return _fnv1a(payload, _fnv1a(head, _fnv1a(
        prev.to_bytes(4, "little"))))


class CDCWriter:
    """Append-only JSONL exporter (see module doc). ``write_batch``
    consumes a decoded ``ReplayBatch`` (the NodeDaemon apply loop);
    ``write_records`` consumes :class:`~...tail.Record`s (the hub
    pump). Both stamp the running chain and the ledger digest."""

    def __init__(self, path: str, *, auditor=None, obs=None,
                 group: int = 0):
        self.path = path
        self.auditor = auditor
        self.obs = obs
        self.default_group = int(group)
        self._chain = {}          # group -> last link value
        self._count = {}          # group -> records written
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def _digest_for(self, group: int, index: int
                    ) -> Tuple[Optional[int], Optional[int]]:
        if self.auditor is None or index < 0:
            return None, None
        ent = self.auditor.digest_at(group, index)
        if ent is None:
            return None, None
        return int(ent[0]), int(ent[1])     # (term, digest)

    def _emit(self, group: int, term: int, index: int, etype: int,
              conn: int, req: int, payload: bytes) -> None:
        prev = self._chain.get(group, 0)
        link = chain_link(prev, group, term, index, etype, conn, req,
                          payload)
        self._chain[group] = link
        dterm, digest = self._digest_for(group, index)
        rec = dict(group=group, term=term, index=index, etype=etype,
                   conn=conn, req=req, payload=payload.hex(),
                   chain=link)
        if digest is not None:
            rec["digest"] = digest
            rec["dterm"] = dterm
        self._f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._count[group] = self._count.get(group, 0) + 1
        if self.obs is not None:
            self.obs.metrics.inc("cdc_exported_total", group=group)

    def write_records(self, group: int, records: Iterable) -> None:
        for r in records:
            self._emit(group, r.term, r.index, r.etype, r.conn,
                       r.req, r.payload)

    def write_batch(self, batch, *, group: Optional[int] = None
                    ) -> None:
        g = self.default_group if group is None else int(group)
        t, c, q, o, b = (batch.types, batch.conns, batch.reqs,
                         batch.offs, batch.blob)
        terms, gidx = batch.terms, batch.gidx
        for i in range(len(batch)):
            self._emit(
                g,
                -1 if terms is None else int(terms[i]),
                -1 if gidx is None else int(gidx[i]),
                int(t[i]), int(c[i]), int(q[i]), b[o[i]:o[i + 1]])

    def exported(self, group: int) -> int:
        return self._count.get(group, 0)

    def flush(self) -> None:
        if not self._f.closed:
            self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()


# ---------------------------------------------------------------------------
# verification
# ---------------------------------------------------------------------------

def _ledger_index(dumps: List[dict]) -> dict:
    """``(group, index) -> (term, digest)`` from one or more
    AuditLedger dumps (``AuditLedger.dump()`` documents — merged
    per-replica files welcome; identical indices must agree, which
    the ledger's own merge already enforced)."""
    out = {}
    for doc in dumps:
        audit = doc.get("audit", doc)   # artifact wrapper or raw dump
        for grp in audit.get("groups", []):
            g = int(grp["group"])
            for si, ent in grp.get("indices", {}).items():
                out[(g, int(si))] = (int(ent[0]), int(ent[1]))
    return out


def verify_export(path: str, ledger_dumps: Optional[List[dict]] = None
                  ) -> dict:
    """Verify a CDC export file. Returns
    ``{ok, records, checked_digests, error, bad}`` where ``bad`` is
    ``(term, index)`` of the FIRST failing record (None when ok).

    Checks, in order per record: JSON well-formedness; per-group
    strictly increasing indices (gaps are legal — non-client entries
    occupy them); chain recomputation from the canonical fields; and,
    when ledger dumps are given, term/digest agreement for every
    index the ledger retains."""
    ledger = _ledger_index(ledger_dumps or [])
    chain = {}
    last_idx = {}
    n = 0
    checked = 0

    def bad(rec, why):
        return dict(ok=False, records=n, checked_digests=checked,
                    error=why,
                    bad=(int(rec.get("term", -1)),
                         int(rec.get("index", -1))))

    with open(path, "r", encoding="utf-8") as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                return dict(ok=False, records=n,
                            checked_digests=checked,
                            error=f"line {ln}: malformed JSON",
                            bad=(-1, -1))
            n += 1
            g = int(rec["group"])
            idx = int(rec["index"])
            term = int(rec["term"])
            if idx >= 0:
                prev_i = last_idx.get(g)
                if prev_i is not None and idx <= prev_i:
                    return bad(rec,
                               f"line {ln}: index {idx} not above "
                               f"previous {prev_i} in group {g}")
                last_idx[g] = idx
            try:
                payload = bytes.fromhex(rec["payload"])
            except ValueError:
                return bad(rec, f"line {ln}: bad payload hex")
            want = chain_link(chain.get(g, 0), g, term, idx,
                              int(rec["etype"]), int(rec["conn"]),
                              int(rec["req"]), payload)
            if want != int(rec["chain"]):
                return bad(rec,
                           f"line {ln}: chain mismatch (record "
                           f"{int(rec['chain'])} != recomputed "
                           f"{want})")
            chain[g] = want
            ent = ledger.get((g, idx))
            if ent is not None:
                lterm, ldig = ent
                if term != lterm:
                    return bad(rec,
                               f"line {ln}: term {term} != ledger "
                               f"term {lterm} at index {idx}")
                if "digest" in rec and int(rec["digest"]) != ldig:
                    return bad(rec,
                               f"line {ln}: digest "
                               f"{int(rec['digest'])} != ledger "
                               f"{ldig} at index {idx}")
                checked += 1
    return dict(ok=True, records=n, checked_digests=checked,
                error=None, bad=None)
