"""CLI: ``python -m rdma_paxos_tpu.streams verify EXPORT [AUDIT...]``

Proves a CDC export end-to-end (see :mod:`.cdc`): per-group strictly
increasing indices, chain recomputation over the canonical record
bytes, and — given one or more AuditLedger dump files (the
``replica<me>.audit.json`` the NodeDaemon writes, or a chaos audit
artifact embedding one) — term/digest agreement for every retained
index. Exit 0 when clean; exit 1 naming the first bad ``(term,
index)``."""

from __future__ import annotations

import argparse
import json
import sys

from rdma_paxos_tpu.streams.cdc import verify_export


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m rdma_paxos_tpu.streams")
    sub = ap.add_subparsers(dest="cmd", required=True)
    v = sub.add_parser("verify", help="verify a CDC export")
    v.add_argument("export", help="CDC JSONL export file")
    v.add_argument("audits", nargs="*",
                   help="AuditLedger dump JSON files to verify "
                        "digests against")
    v.add_argument("--json", action="store_true",
                   help="emit the verdict as JSON")
    args = ap.parse_args(argv)

    dumps = []
    for path in args.audits:
        with open(path, "r", encoding="utf-8") as f:
            dumps.append(json.load(f))
    verdict = verify_export(args.export, dumps)
    if args.json:
        print(json.dumps(verdict, indent=2))
    elif verdict["ok"]:
        print(f"OK: {verdict['records']} records, "
              f"{verdict['checked_digests']} ledger digests checked")
    else:
        term, index = verdict["bad"]
        print(f"FAIL at (term={term}, index={index}): "
              f"{verdict['error']}", file=sys.stderr)
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
