"""Watch/subscribe — committed-delta fan-out with exactly-once resume.

A :class:`WatchHub` tails each group's committed stream from its own
pump thread (NEVER the readback thread: the engine finish() tail only
kicks a condition variable, so a slow or wedged consumer can never
delay the data path or the ReadHub's queued point reads — the
drain-path decoupling this PR pins by test). Per wake the pump
advances a per-group cursor, decodes the new records once, applies
the app fold's exactly-once acceptance rule (``DedupFold`` — the
mirror of ``ReplicatedKVS._fold``), and fans matching key-range
events into per-subscription BOUNDED deques. Clients pull with
:meth:`Subscription.next`/:meth:`Subscription.poll`; a subscription
that falls ``queue_cap`` behind is marked overflowed and must
reconnect with its resume token — backpressure surfaces as an
explicit resume, never an unbounded queue.

Resume tokens name the last consumed event in the audit chain's own
coordinates ``(group, term, absolute index)`` and additionally carry
the event's stream POSITION — the replay cursor. The hub retains the
last ``retain`` post-fold events per group; a reconnect with a token
replays retained events past the token's position into the fresh
queue before going live — zero duplicates, zero gaps, across leader
failover, lease revocation, and client reconnect. Positions anchor
the replay because they are ALWAYS known (an entry that lost its
decoded coordinates — e.g. via a legacy tuple-view materialization of
the donor stream — still has its position) and failover-stable: the
committed prefix never shrinks and every replica applies the same
committed order, so position k names the same entry on any donor.

Host-pure; shared state guarded by ``_wlock`` (the condition's lock —
static lock-discipline pass + RP_SANITIZE runtime sanitizer).
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional

from rdma_paxos_tpu.streams.tail import (
    DedupFold, GroupTail, OP_PUT, OP_RM, decode_kvs)


class ResumeExpired(RuntimeError):
    """The resume token points before the hub's retained event window
    — the events needed for a gapless replay are gone."""


class WatchEvent:
    """One exactly-once committed delta."""

    __slots__ = ("group", "term", "index", "pos", "op", "key", "val",
                 "conn", "req")

    def __init__(self, group, term, index, pos, op, key, val, conn,
                 req):
        self.group = group
        self.term = term
        self.index = index     # absolute log index (resume coordinate)
        self.pos = pos
        self.op = op           # OP_PUT | OP_RM
        self.key = key
        self.val = val
        self.conn = conn
        self.req = req

    def token(self) -> dict:
        """Resume token naming THIS event as the last consumed."""
        return dict(group=self.group, term=self.term,
                    index=self.index, pos=self.pos)

    def __repr__(self) -> str:
        return (f"WatchEvent(g={self.group} t={self.term} "
                f"i={self.index} op={self.op} key={self.key!r})")


class Subscription:
    """One client's bounded event queue over a key range."""

    def __init__(self, hub: "WatchHub", sub_id: int, group: int,
                 lo: bytes, hi: Optional[bytes], cap: int):
        self.hub = hub
        self.sub_id = sub_id
        self.group = group
        self.lo = lo
        self.hi = hi
        self.cap = cap
        self.queue: collections.deque = collections.deque()
        self.overflowed = False
        self.closed = False
        self.fail_reason: Optional[str] = None
        self.delivered = 0
        self.last_ev = None    # last popped event (token anchor)

    def _matches(self, ev: WatchEvent) -> bool:
        # group first: the pump fans each group's decoded batch over
        # ALL subscriptions, so key-range alone would leak a sibling
        # group's events into this queue (G > 1)
        return (ev.group == self.group
                and ev.key >= self.lo
                and (self.hi is None or ev.key < self.hi))

    def poll(self, max_n: int = 64) -> List[WatchEvent]:
        """Up to ``max_n`` pending events (non-blocking)."""
        return self.hub._pop(self, max_n, timeout=None)

    def next(self, timeout: Optional[float] = None
             ) -> Optional[WatchEvent]:
        """Block up to ``timeout`` for one event; None on timeout or
        closed-and-drained."""
        got = self.hub._pop(self, 1, timeout=timeout)
        return got[0] if got else None

    def token(self) -> Optional[dict]:
        """Resume token of the last CONSUMED event (None before the
        first pop — resume-from-start)."""
        last = self.last_ev
        return None if last is None else last.token()

    def close(self) -> None:
        self.hub.unsubscribe(self.sub_id)


class WatchHub:
    """Per-group watch cursors + the pump thread (see module doc)."""

    def __init__(self, tails: List[GroupTail], *, obs=None,
                 queue_cap: int = 1024, retain: int = 1 << 16,
                 cdc=None):
        self.obs = obs
        self.queue_cap = int(queue_cap)
        self.retain = int(retain)
        self.cdc = cdc
        self._tails = {t.group: t for t in tails}
        self._wlock = threading.Lock()
        self._wcv = threading.Condition(self._wlock)
        # guarded-by: _wlock
        self._wsubs: Dict[int, Subscription] = {}
        # guarded-by: _wlock
        self._wcursor: Dict[int, int] = {t.group: 0 for t in tails}
        # guarded-by: _wlock
        self._wtarget: Dict[int, int] = {t.group: 0 for t in tails}
        # guarded-by: _wlock
        self._wevents: Dict[int, List[WatchEvent]] = {
            t.group: [] for t in tails}
        # guarded-by: _wlock
        self._wfold: Dict[int, DedupFold] = {
            t.group: DedupFold() for t in tails}
        # highest event position/index ever trimmed from retention
        # per group (-1 = nothing trimmed): the EXACT resume-gap
        # bound — a token at/under it cannot replay gapless
        # guarded-by: _wlock
        self._wtrim: Dict[int, int] = {t.group: -1 for t in tails}
        # guarded-by: _wlock
        self._wtrimidx: Dict[int, int] = {t.group: -1 for t in tails}
        self._wnext_id = 1        # guarded-by: _wlock
        self._wstopped = False    # guarded-by: _wlock
        self.events_total = 0     # guarded-by: _wlock
        # earliest un-dispatched kick timestamp per group — anchors a
        # watch-delivery trace at COMMIT time, so the merged timeline
        # shows commit → pump → deliver. Only populated while the
        # trace plane samples.  # guarded-by: _wlock
        self._wkick: Dict[int, float] = {}
        from rdma_paxos_tpu.analysis import runtime_guard
        runtime_guard.maybe_guard(self, "_wlock", __file__)
        self._pump = threading.Thread(
            target=self._pump_loop, name="watch-pump", daemon=True)
        self._pump.start()

    # ---------------- client surface ----------------

    def subscribe(self, group: int = 0, *, lo: bytes = b"",
                  hi: Optional[bytes] = None,
                  token: Optional[dict] = None,
                  cap: Optional[int] = None) -> Subscription:
        """Open a subscription over ``[lo, hi)`` of ``group``. With a
        resume ``token``, retained events past the token replay into
        the queue first — gapless, duplicate-free — then live events
        follow."""
        with self._wlock:
            if self._wstopped:
                raise RuntimeError("watch hub stopped")
            sub = Subscription(self, self._wnext_id, int(group),
                               bytes(lo), hi, self.queue_cap
                               if cap is None else int(cap))
            self._wnext_id += 1
            if token is not None:
                if int(token["group"]) != int(group):
                    raise ValueError("token group mismatch")
                tpos = token.get("pos")
                if tpos is not None:
                    # position-anchored replay (the robust path: every
                    # event has one — see the module docstring)
                    tpos = int(tpos)
                    if tpos < self._wtrim[sub.group]:
                        # an event past the token was trimmed from
                        # retention — a replay would silently gap
                        raise ResumeExpired(
                            f"resume position {tpos} precedes the "
                            f"retained window (trimmed through "
                            f"{self._wtrim[sub.group]})")
                    for ev in self._wevents[sub.group]:
                        if ev.pos > tpos and sub._matches(ev):
                            sub.queue.append(ev)
                else:
                    # coordinate-only token (external/persisted form)
                    after = int(token["index"])
                    if after < self._wtrimidx[sub.group]:
                        raise ResumeExpired(
                            f"resume index {after} precedes the "
                            f"retained window (trimmed through "
                            f"{self._wtrimidx[sub.group]})")
                    for ev in self._wevents[sub.group]:
                        if ev.index > after and sub._matches(ev):
                            sub.queue.append(ev)
            self._wsubs[sub.sub_id] = sub
            return sub

    def unsubscribe(self, sub_id: int) -> None:
        with self._wlock:
            sub = self._wsubs.pop(sub_id, None)
            if sub is not None:
                sub.closed = True
            self._wcv.notify_all()

    def _pop(self, sub: Subscription, max_n: int,
             timeout: Optional[float]) -> List[WatchEvent]:
        with self._wlock:
            if timeout is not None:
                self._wcv.wait_for(
                    lambda: sub.queue or sub.closed or self._wstopped,
                    timeout)
            out = []
            while sub.queue and len(out) < max_n:
                out.append(sub.queue.popleft())
            if out:
                sub.last_ev = out[-1]
            return out

    # ---------------- engine-side surface ----------------

    def kick(self, lengths: Dict[int, int]) -> None:
        """New committed frontier (engine finish() tail, readback
        thread): record per-group targets and wake the pump. O(G) —
        never decodes, never blocks on a consumer."""
        from rdma_paxos_tpu.obs.tracectx import active_tracer
        tr = active_tracer(self.obs)
        with self._wlock:
            for g, n in lengths.items():
                if n > self._wtarget.get(g, 0):
                    self._wtarget[g] = n
                    if tr is not None:
                        # keep the EARLIEST pending kick: latency is
                        # measured from the first commit the pump has
                        # not yet caught up to
                        self._wkick.setdefault(g, tr.now())
            self._wcv.notify_all()

    def wait_caught_up(self, lengths: Dict[int, int],
                       timeout: float = 10.0) -> bool:
        """Kick the pump to the given per-group frontiers and block
        until its cursors reach them (or ``timeout``/stop). The
        flush primitive for run-end drains — callers in
        replay-deterministic modules (the chaos runner) must not spin
        on wall clock themselves."""
        # holds-lock: _wlock  (wait_for invokes the predicate held)
        def ready():
            return self._wstopped or all(
                self._wcursor.get(g, 0) >= int(n)
                for g, n in lengths.items())
        with self._wlock:
            for g, n in lengths.items():
                if int(n) > self._wtarget.get(g, 0):
                    self._wtarget[g] = int(n)
            self._wcv.notify_all()
            self._wcv.wait_for(ready, timeout)
            return all(self._wcursor.get(g, 0) >= int(n)
                       for g, n in lengths.items())

    def cursors(self) -> Dict[int, int]:
        """Per-group pump positions (CDC lag = tail - cursor)."""
        with self._wlock:
            return dict(self._wcursor)

    def backlogs(self) -> Dict[int, int]:
        """Per-group undispatched depth (target - cursor) plus the
        deepest subscriber queue — the governor reads this as demand."""
        with self._wlock:
            out = {}
            for g in self._wcursor:
                lag = self._wtarget.get(g, 0) - self._wcursor[g]
                qmax = max((len(s.queue) for s in self._wsubs.values()
                            if s.group == g), default=0)
                out[g] = max(0, lag) + qmax
            return out

    # ---------------- pump ----------------

    def _pump_loop(self) -> None:
        # lock order: _wlock is NEVER held across the tail snapshot
        # (which takes the engine host lock) — the governor reads
        # backlogs() without the host lock, so no cycle exists
        while True:
            with self._wlock:
                self._wcv.wait_for(
                    lambda: self._wstopped or any(
                        self._wtarget.get(g, 0) > c
                        for g, c in self._wcursor.items()))
                if self._wstopped:
                    return
                work = [(g, c, self._wtarget.get(g, 0))
                        for g, c in self._wcursor.items()
                        if self._wtarget.get(g, 0) > c]
            for g, lo, hi in work:
                recs = self._tails[g].records(lo, hi)
                self._dispatch(g, lo, hi, recs)

    def _dispatch(self, g: int, lo: int, hi: int, recs) -> None:
        from rdma_paxos_tpu.obs.tracectx import active_tracer
        tr = active_tracer(self.obs)
        tid = None
        if tr is not None:
            with self._wlock:
                k0 = self._wkick.pop(g, None)
            # t0 = the kick (commit frontier advance); "pump" marks
            # when the pump thread actually picked the batch up
            tid = tr.begin("watch", ts=k0, group=g, lo=lo, hi=hi)
            tr.phase(tid, "pump")
        if self.cdc is not None:
            self.cdc.write_records(g, recs)
        events = []
        with self._wlock:
            fold = self._wfold[g]
            for rec in recs:
                if not fold.accept(rec):
                    continue
                cmd = decode_kvs(rec.payload)
                if cmd is None:
                    continue
                op, key, val = cmd
                if op not in (OP_PUT, OP_RM):
                    continue
                events.append(WatchEvent(
                    g, rec.term, rec.index, rec.pos, op, key, val,
                    rec.conn, rec.req))
            self._wcursor[g] = max(self._wcursor[g], hi)
            retained = self._wevents[g]
            retained.extend(events)
            if len(retained) > self.retain:
                cut = len(retained) - self.retain
                self._wtrim[g] = max(self._wtrim[g],
                                     retained[cut - 1].pos)
                self._wtrimidx[g] = max(
                    [self._wtrimidx[g]]
                    + [e.index for e in retained[:cut]
                       if e.index >= 0])
                del retained[:cut]
            delivered = 0
            for sub in self._wsubs.values():
                for ev in events:
                    if not sub._matches(ev):
                        continue
                    if len(sub.queue) >= sub.cap:
                        sub.overflowed = True
                        break
                    sub.queue.append(ev)
                    sub.delivered += 1
                    delivered += 1
            self.events_total += delivered
            self._wcv.notify_all()
        if tid is not None:
            tr.phase(tid, "deliver")
            tr.end(tid, events=len(events), delivered=delivered)
        if self.obs is not None and events:
            self.obs.metrics.inc("watch_events_delivered_total",
                                 delivered, group=g)

    # ---------------- lifecycle ----------------

    def fail_all(self, reason: str) -> None:
        """Stop the pump and close every subscription (driver stop
        path — mirrors ``ReadHub.fail_all``): a watcher blocked in
        ``next()`` wakes with the queue drained and ``closed`` set,
        never hangs on a dead engine."""
        with self._wlock:
            if self._wstopped:
                return
            self._wstopped = True
            for sub in self._wsubs.values():
                sub.closed = True
                sub.fail_reason = reason
            self._wcv.notify_all()
        self._pump.join(timeout=5.0)
        if self.cdc is not None:
            self.cdc.flush()

    def status(self) -> dict:
        with self._wlock:
            return dict(
                subs=len(self._wsubs),
                events_total=self.events_total,
                cursors=dict(self._wcursor),
                targets=dict(self._wtarget),
                overflowed=sum(1 for s in self._wsubs.values()
                               if s.overflowed),
                stopped=self._wstopped)
