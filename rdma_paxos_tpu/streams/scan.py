"""Ordered range scans with consistent-cut pagination.

A scan rides the PR 10 ReadHub: ONE batched read-index confirm per
page (lease-served when the replica holds a valid lease), then the
page is computed at the linearization point — on the readback thread,
from the host-side key index this module folds out of the committed
stream. The first page pins a **consistent cut**: the stream position
at serve time (failover-stable — the committed prefix never shrinks
and rebases renumber slots, not stream entries), named in the token
by the log's own ``(term, index)`` coordinates. Every later page
resolves values AS OF that cut, so pagination never tears across a
leader failover: a key overwritten or deleted mid-scan still pages
out with its at-cut value via the MVCC-lite undo log recorded while
the pin is active.

Pins expire after ``pin_steps`` finished engine steps (an abandoned
scan must not grow the undo log forever); an expired token is an
explicit ``token-expired`` error — restart the scan — never a silent
tear.

Host-pure; all shared state is guarded by the manager's ``_slock``
(static lock-discipline pass + RP_SANITIZE runtime sanitizer).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from rdma_paxos_tpu.streams.tail import (
    DedupFold, GroupTail, OP_PUT, OP_RM, decode_kvs)

_MISSING = None     # "absent at the cut" sentinel in undo entries


class TokenExpired(RuntimeError):
    """The scan token's cut pin lapsed (pin_steps elapsed) — the
    at-cut values are gone; restart the scan."""


def key_range(prefix: Optional[bytes] = None,
              lo: Optional[bytes] = None,
              hi: Optional[bytes] = None
              ) -> Tuple[bytes, Optional[bytes]]:
    """Normalize ``prefix`` | ``[lo, hi)`` into ``(lo, hi)`` bounds
    (``hi`` None = +inf). A prefix becomes its tight byte range."""
    if prefix is not None:
        if lo is not None or hi is not None:
            raise ValueError("prefix and lo/hi are exclusive")
        lo = bytes(prefix)
        p = bytearray(prefix)
        while p and p[-1] == 0xFF:
            p.pop()
        if p:
            p[-1] += 1
            hi = bytes(p)
        else:
            hi = None           # prefix of all 0xFF: unbounded above
        return lo, hi
    return (b"" if lo is None else bytes(lo),
            None if hi is None else bytes(hi))


def groups_for_range(router, lo: bytes,
                     hi: Optional[bytes]) -> Optional[List[int]]:
    """Router-aware fan-out narrowing: when a single range override
    fully covers ``[lo, hi)``, only that group can hold keys in the
    range; otherwise the hash ring scatters — every group serves.
    None = all groups (no router)."""
    if router is None:
        return None
    for rule in getattr(router, "overrides", ()):
        if lo >= rule.lo and (rule.hi is None
                              or (hi is not None and hi <= rule.hi)):
            return [rule.group]
    return list(range(router.n_groups))


class _GroupScanIndex:
    """One group's host-side sorted-key fold of the committed stream,
    plus the MVCC-lite undo log for pinned cuts. All access under the
    owning :class:`ScanManager`'s ``_slock`` (methods are ``_locked``
    by the lock-discipline convention)."""

    def __init__(self, tail: GroupTail):
        self.tail = tail
        self.vals: Dict[bytes, bytes] = {}
        self.fold = DedupFold()
        self.pos = 0                   # stream position folded through
        self.coord = (-1, -1)          # (term, index) at self.pos
        # undo log: key -> [(pos, prior_value_or_None)...] ascending,
        # recorded for every mutation applied while ANY pin is active
        self.undo: Dict[bytes, List[tuple]] = {}
        self.pins: Dict[int, int] = {}   # cut_pos -> expiry step

    def catch_up_locked(self) -> None:
        """Fold new committed records into the key index (records the
        undo entry for each mutation while pins are active)."""
        recs = self.tail.records(self.pos)
        pinned = bool(self.pins)
        for rec in recs:
            if rec.index >= 0:
                self.coord = (rec.term, rec.index)
            self.pos = rec.pos + 1
            if not self.fold.accept(rec):
                continue
            cmd = decode_kvs(rec.payload)
            if cmd is None:
                continue
            op, key, val = cmd
            if op == OP_PUT:
                if pinned:
                    self.undo.setdefault(key, []).append(
                        (rec.pos, self.vals.get(key, _MISSING)))
                self.vals[key] = val
            elif op == OP_RM and key in self.vals:
                if pinned:
                    self.undo.setdefault(key, []).append(
                        (rec.pos, self.vals[key]))
                del self.vals[key]

    def resolve_locked(self, key: bytes,
                       cut_pos: int) -> Optional[bytes]:
        """The value of ``key`` AS OF the cut: the prior value of the
        first recorded mutation past the cut, else the current value.
        Correct because the cut's pin was registered before any
        record past ``cut_pos`` was folded, so every later mutation
        has an undo entry."""
        for pos, prior in self.undo.get(key, ()):
            if pos >= cut_pos:
                return prior
        return self.vals.get(key, _MISSING)

    def page_locked(self, lo: bytes, hi: Optional[bytes],
                    after: Optional[bytes], limit: int,
                    cut_pos: int) -> List[Tuple[bytes, bytes]]:
        """Up to ``limit`` ``(key, at-cut value)`` pairs in key order,
        strictly after ``after``. Candidates include undo-only keys —
        a key deleted after the cut still existed AT the cut."""
        cands = set(self.vals)
        cands.update(self.undo)
        out: List[Tuple[bytes, bytes]] = []
        for key in sorted(cands):
            if key < lo or (hi is not None and key >= hi):
                continue
            if after is not None and key <= after:
                continue
            val = self.resolve_locked(key, cut_pos)
            if val is _MISSING:
                continue
            out.append((key, val))
            if len(out) >= limit:
                break
        return out

    def gc_locked(self) -> None:
        if not self.pins:
            self.undo.clear()
            return
        floor = min(self.pins)
        for key in list(self.undo):
            kept = [e for e in self.undo[key] if e[0] >= floor]
            if kept:
                self.undo[key] = kept
            else:
                del self.undo[key]


class ScanManager:
    """Per-group scan indexes + cut-pin lifecycle. Folding happens
    ONLY on scan serves (zero steady-state cost when nobody scans);
    pin expiry ticks on the hub's per-step observe."""

    def __init__(self, tails: List[GroupTail], *,
                 pin_steps: int = 512, obs=None):
        self.pin_steps = int(pin_steps)
        self.obs = obs
        self._slock = threading.Lock()
        # guarded-by: _slock
        self._sidx: Dict[int, _GroupScanIndex] = {
            t.group: _GroupScanIndex(t) for t in tails}
        self._sstep = 0       # guarded-by: _slock
        self.pages_served = 0     # guarded-by: _slock
        self.pins_expired = 0     # guarded-by: _slock
        from rdma_paxos_tpu.analysis import runtime_guard
        runtime_guard.maybe_guard(self, "_slock", __file__)

    def on_step(self) -> None:
        """Pin-expiry tick (engine finish() tail, readback thread)."""
        with self._slock:
            self._sstep += 1
            step = self._sstep
            for idx in self._sidx.values():
                expired = [c for c, dl in idx.pins.items()
                           if dl <= step]
                for c in expired:
                    del idx.pins[c]
                    self.pins_expired += 1
                if expired:
                    idx.gc_locked()

    def pin_count(self) -> int:
        with self._slock:
            return sum(len(i.pins) for i in self._sidx.values())

    def serve_page(self, group: int, lo: bytes, hi: Optional[bytes],
                   after: Optional[bytes], limit: int,
                   cut_pos: Optional[int], kvs=None) -> dict:
        """ONE page at the linearization point (ReadHub serve
        callback, readback thread). ``cut_pos`` None = first page:
        pin a fresh cut at the current stream end. Returns
        ``{items, cut, term, index, done}`` or ``{error}``."""
        with self._slock:
            idx = self._sidx[group]
            if cut_pos is None:
                # pin BEFORE folding: every record folded past the
                # cut must leave an undo entry for resolve()
                cut_pos = idx.tail.length()
                idx.pins[cut_pos] = self._sstep + self.pin_steps
            elif cut_pos not in idx.pins:
                return dict(error="token-expired")
            else:
                idx.pins[cut_pos] = self._sstep + self.pin_steps
            idx.catch_up_locked()
            items = idx.page_locked(lo, hi, after, limit, cut_pos)
            if kvs is not None and items:
                # serve values through the tiered device dispatch for
                # keys NOT mutated past the cut (their at-cut value is
                # the current applied value); post-cut-mutated keys
                # keep the host-resolved at-cut value
                plain = [k for k, _ in items if k not in idx.undo]
                if plain:
                    got = self._device_vals(kvs, group, plain)
                    if got is not None:
                        merged = dict(items)
                        for k, v in zip(plain, got):
                            if v is not None:
                                merged[k] = v
                        items = sorted(merged.items())
            # done = this group has nothing past this page; the HUB
            # releases the pin once the whole (possibly multi-group)
            # scan completes — a short page here may still be
            # re-queried after a cross-group merge
            done = len(items) < limit
            self.pages_served += 1
            term, index = idx.coord
            if self.obs is not None:
                self.obs.metrics.inc("scan_pages_total", group=group)
            return dict(items=items, cut=cut_pos, term=term,
                        index=index, done=done)

    def _device_vals(self, kvs, group: int, keys: List[bytes]):
        """Batched values via ``ReplicatedKVS.get_many`` at the
        group's serving replica; None on any failure (host values are
        always a correct fallback)."""
        try:
            kv = kvs.groups[group] if hasattr(kvs, "groups") else kvs
            lm = getattr(kv.c, "leases", None)
            rep = -1
            if lm is not None:
                rep = lm.serving_holder(getattr(kv, "group", 0) or 0)
            if rep is None or rep < 0:
                rep = 0
            return kv.get_many(rep, keys)
        except Exception:  # noqa: BLE001 — fallback, never fail serve
            return None

    def release(self, group: int, cut_pos: int) -> None:
        with self._slock:
            idx = self._sidx.get(group)
            if idx is not None and idx.pins.pop(cut_pos, None) \
                    is not None:
                idx.gc_locked()

    def status(self) -> dict:
        with self._slock:
            return dict(
                pages_served=self.pages_served,
                pins_expired=self.pins_expired,
                pins={g: sorted(i.pins) for g, i in
                      self._sidx.items() if i.pins},
                folded={g: i.pos for g, i in self._sidx.items()})
