"""Quorum commit scan — the hot op of the consensus core.

Reference: on every iteration of the replication loop the DARE leader decides
commit by scanning entries in ``(commit, end]`` and counting per-entry ACK
bytes that followers RDMA-wrote into the entry's ``reply[]`` array; an entry
is committed iff the count reaches a majority, and during membership
transitions iff it reaches *both* majorities (``dare_ibv_rc.c:1725-1758``,
dual-quorum ``:2799-2957``; ``wait_for_majority`` ``:2768-2964``).

TPU-native formulation: followers acknowledge by advertising their ``end``
offset (an ``all_gather``), so the per-entry ACK bitmap is implicit:
``ack[j, r] = (end_r > commit + j)``. The scan materializes that bitmap as a
``[W, R_PAD]`` tile in VMEM, popcounts each row under the member bitmask(s),
takes the contiguous committed prefix, and applies the Raft current-term
guard (a leader only commits entries of its own term; earlier-term entries
commit transitively — the reason the reference leader appends a blank NOOP
entry on election, ``dare_server.c:1403-1491``). The result is a **monotone**
commit-index advance.

Two interchangeable implementations:

* :func:`commit_scan_ref` — pure ``jax.numpy``; runs anywhere, used as the
  test oracle and the CPU-simulation path.
* :func:`commit_scan_pallas` — Pallas TPU kernel; one VMEM tile, VPU-only.

Both are pure element-wise/reduction code on a ``[W, R_PAD]`` tile, so XLA
also fuses the reference version well; the kernel exists to keep the scan in
a single VMEM-resident pass. Production paths (SimCluster,
HostReplicaDriver) default to the Pallas kernel on TPU — the same code
path as the benches.

FUSION RESULT (measured, round 3): extending the kernel across the whole
ack-aggregate + window-select + commit stage is a NULL result by
construction and by measurement. The ack aggregate is a
``lax.all_gather`` and the window select consumes another gather's
output — cross-replica collectives that cannot live inside a
single-replica Pallas kernel without remote DMAs; everything element-wise
around them is already fused by XLA into the collectives' prologue/
epilogue. Measured on TPU v5e (64-step scans, batch 1024, R=3): full
step 479 µs with the Pallas scan vs 465 µs with the jnp scan — parity
within run-to-run noise (~3%), confirming the scan tile ([W, 128] i32)
is nowhere near the step's critical path (the window gather/scatter and
ring scans are). The kernel is kept as the single-VMEM-pass form and the
seed for a future multi-chip kernel that overlaps the quorum scan with
the window DMA.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

R_PAD = 128   # lane-width padding of the replica axis (MAX_SERVER_COUNT=13)


def _scan_math(ends, commit, my_term, my_end, terms_win, bm_old, bm_new,
               transit, maj_old, maj_new, W):
    """Shared scan body: ends [R_PAD] i32 (non-members already zeroed) ->
    new commit (scalar i32, >= commit)."""
    j = jax.lax.broadcasted_iota(jnp.int32, (W, R_PAD), 0)    # entry row
    r = jax.lax.broadcasted_iota(jnp.int32, (W, R_PAD), 1)    # replica col

    in_old = jnp.bitwise_and(
        jnp.right_shift(bm_old, r.astype(jnp.uint32)), 1).astype(jnp.int32)
    in_new = jnp.bitwise_and(
        jnp.right_shift(bm_new, r.astype(jnp.uint32)), 1).astype(jnp.int32)

    ack = (ends[None, :] > commit + j).astype(jnp.int32)      # [W, R_PAD]
    cnt_old = jnp.sum(ack * in_old, axis=1)                   # [W]
    cnt_new = jnp.sum(ack * in_new, axis=1)

    jcol = jnp.arange(W, dtype=jnp.int32)
    ok = (cnt_new >= maj_new) & (commit + jcol < my_end)
    # boolean algebra, not where-on-bool (Mosaic can't legalize i1 selects)
    ok = ok & ((transit <= 0) | (cnt_old >= maj_old))

    # contiguous committed prefix length = first False position (plain min
    # reduction — integer arg-reductions don't lower on the TPU VPU)
    prefix = jnp.min(jnp.where(ok, W, jcol))

    # Raft term guard: commit only up to the last current-term entry in the
    # prefix (entries of older terms commit transitively below it).
    eligible = (jcol < prefix) & (terms_win == my_term)
    lastj = jnp.max(jnp.where(eligible, jcol, -1))
    return jnp.where(lastj >= 0, commit + lastj + 1, commit).astype(jnp.int32)


def commit_scan_ref(
    ends: jax.Array,        # [R_PAD] i32 — gathered end offsets, 0 for
                            #   non-members / unreachable replicas
    commit: jax.Array,      # scalar i32 — current commit index
    my_term: jax.Array,     # scalar i32 — leader's term
    my_end: jax.Array,      # scalar i32 — leader's end
    terms_win: jax.Array,   # [W] i32 — terms of entries commit .. commit+W-1
    bitmask_old: jax.Array,  # scalar u32
    bitmask_new: jax.Array,  # scalar u32
    transit: jax.Array,     # scalar i32 — 1 if joint consensus active
    maj_old: jax.Array,     # scalar i32
    maj_new: jax.Array,     # scalar i32
) -> jax.Array:
    W = terms_win.shape[0]
    return _scan_math(ends, commit, my_term, my_end, terms_win,
                      bitmask_old, bitmask_new, transit, maj_old, maj_new, W)


def _kernel(scal_ref, ends_ref, terms_ref, out_ref):
    W = terms_ref.shape[1]
    result = _scan_math(
        ends=ends_ref[0, :],
        commit=scal_ref[0, 0],
        my_term=scal_ref[0, 1],
        my_end=scal_ref[0, 2],
        terms_win=terms_ref[0, :],
        bm_old=scal_ref[0, 3].astype(jnp.uint32),
        bm_new=scal_ref[0, 4].astype(jnp.uint32),
        transit=scal_ref[0, 5],
        maj_old=scal_ref[0, 6],
        maj_new=scal_ref[0, 7],
        W=W,
    )
    # VPU stores are vector-shaped: broadcast the scalar across the row
    out_ref[:, :] = jnp.broadcast_to(result, (1, out_ref.shape[1]))


@functools.partial(jax.jit, static_argnames=("interpret",))
def commit_scan_pallas(ends, commit, my_term, my_end, terms_win,
                       bitmask_old, bitmask_new, transit, maj_old, maj_new,
                       *, interpret: bool = False) -> jax.Array:
    """Pallas TPU version of :func:`commit_scan_ref` (same signature).

    All operands ride in VMEM as (1, lane)-shaped i32 rows — no SMEM
    blocks — so the call stays batchable: under ``vmap`` (the single-chip
    multi-replica simulation) the batch dim lifts into the Pallas grid.
    """
    W = terms_win.shape[0]
    scal = jnp.zeros((1, R_PAD), jnp.int32)
    for i, v in enumerate([commit, my_term, my_end, bitmask_old,
                           bitmask_new, transit, maj_old, maj_new]):
        scal = scal.at[0, i].set(v.astype(jnp.int32))
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((1, R_PAD), jnp.int32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(scal, ends.reshape(1, R_PAD), terms_win.reshape(1, W))
    return out[0, 0]


def commit_scan(*args, use_pallas: bool = False, interpret: bool = False):
    """Dispatcher: Pallas on TPU, jnp elsewhere (same semantics)."""
    if use_pallas:
        return commit_scan_pallas(*args, interpret=interpret)
    return commit_scan_ref(*args)
