from rdma_paxos_tpu.ops.quorum import (  # noqa: F401
    commit_scan,
    commit_scan_ref,
    commit_scan_pallas,
)
