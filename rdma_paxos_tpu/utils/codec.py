"""Byte-stream ↔ int32-word marshalling for log slots.

The reference moves raw bytes (client TCP payloads ≤ 87380 B,
``src/include/dare/message.h:5-9``) straight into log entries. The TPU log
stores payloads as int32 words; the proxy fragments anything larger than one
slot into consecutive SEND entries (lossless for stream replay)."""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def bytes_to_words(payload: bytes, slot_words: int) -> np.ndarray:
    """Pack bytes into an int32 word row (zero-padded). len(payload) must
    fit one slot; the proxy fragments above."""
    if len(payload) > slot_words * 4:
        raise ValueError("payload exceeds slot capacity; fragment first")
    buf = payload + b"\x00" * (slot_words * 4 - len(payload))
    return np.frombuffer(buf, dtype="<i4").copy()


def words_to_bytes(words: np.ndarray, length: int) -> bytes:
    return words.astype("<i4").tobytes()[:length]


def fragment(payload: bytes, slot_bytes: int) -> List[bytes]:
    """Split an oversize payload into slot-sized chunks (proxy-side;
    replay concatenates them back in log order)."""
    if not payload:
        return [b""]
    return [payload[i:i + slot_bytes]
            for i in range(0, len(payload), slot_bytes)]
