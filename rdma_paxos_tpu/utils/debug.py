"""Per-replica observability log — the ``debug.h`` analog.

The reference writes timestamped protocol events to a per-server file
(``info/info_wtime`` macros, ``src/include/dare/debug.h:24-106``; file from
env ``dare_log_file``, ``proxy.c:57-69``), and the benchmark driver finds
the leader by grepping ``"] LEADER"`` from those logs
(``benchmarks/run.sh:47-70``, printed at ``dare_server.c:1396``). The exact
same grep works against these files: on winning an election the driver
writes ``[T<term>] LEADER``.
"""

from __future__ import annotations

import os
import time
from typing import Optional, TextIO


class ReplicaLog:
    def __init__(self, path: Optional[str] = None):
        self._f: Optional[TextIO] = open(path, "a") if path else None
        self._t0 = time.time()

    def info(self, msg: str) -> None:
        if self._f is None:
            return
        self._f.write(msg + "\n")
        self._f.flush()

    def info_wtime(self, msg: str) -> None:
        """Wall-clock-stamped event line (info_wtime analog)."""
        if self._f is None:
            return
        now = time.time()
        self._f.write(f"[{now:.6f} +{now - self._t0:8.3f}s] {msg}\n")
        self._f.flush()

    def leader_elected(self, term: int) -> None:
        """The exact greppable leader line of the reference
        (``"[T%d] LEADER"``, dare_server.c:1396, grepped by run.sh)."""
        self.info_wtime(f"[T{term}] LEADER")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class StepTimer:
    """rdtsc-style section timing (timer.h TIMER_START/STOP analog) with
    µs resolution, accumulated per label."""

    def __init__(self):
        self.acc = {}
        self._open = {}

    def start(self, label: str) -> None:
        self._open[label] = time.perf_counter_ns()

    def stop(self, label: str) -> None:
        t0 = self._open.pop(label, None)
        if t0 is not None:
            us = (time.perf_counter_ns() - t0) / 1e3
            n, tot, mx = self.acc.get(label, (0, 0.0, 0.0))
            self.acc[label] = (n + 1, tot + us, max(mx, us))

    def report(self) -> str:
        lines = []
        for label, (n, tot, mx) in sorted(self.acc.items()):
            lines.append(f"{label}: n={n} mean={tot / max(n, 1):.1f}us "
                         f"max={mx:.1f}us")
        return "\n".join(lines)
