"""Per-replica observability log — the ``debug.h`` analog.

The reference writes timestamped protocol events to a per-server file
(``info/info_wtime`` macros, ``src/include/dare/debug.h:24-106``; file from
env ``dare_log_file``, ``proxy.c:57-69``), and the benchmark driver finds
the leader by grepping ``"] LEADER"`` from those logs
(``benchmarks/run.sh:47-70``, printed at ``dare_server.c:1396``). The exact
same grep works against these files: on winning an election the driver
writes ``[T<term>] LEADER``.

Routed through :mod:`rdma_paxos_tpu.obs` when an ``obs`` facade is
attached: the greppable ``"[T%d] LEADER"`` FILE line is preserved
verbatim (the run.sh contract), while every event additionally lands as
a structured trace event (and ``leader_elected`` as an
``elections_won_total`` counter) — so operators keep their grep and the
harness gets typed data.
"""

from __future__ import annotations

import time
from typing import Optional, TextIO

from rdma_paxos_tpu.obs.metrics import LATENCY_BUCKETS_US


class ReplicaLog:
    def __init__(self, path: Optional[str] = None, *,
                 replica: int = -1, obs=None):
        self._f: Optional[TextIO] = open(path, "a") if path else None
        self._t0 = time.time()
        self.replica = replica
        self.obs = obs            # Observability facade or None

    def info(self, msg: str) -> None:
        if self.obs is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.trace.record(_trace.LOG_LINE, replica=self.replica,
                                  msg=msg)
        if self._f is None:
            return
        self._f.write(msg + "\n")
        self._f.flush()

    def _write_wtime(self, msg: str) -> None:
        if self._f is None:
            return
        now = time.time()
        self._f.write(f"[{now:.6f} +{now - self._t0:8.3f}s] {msg}\n")
        self._f.flush()

    def info_wtime(self, msg: str) -> None:
        """Wall-clock-stamped event line (info_wtime analog)."""
        if self.obs is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.trace.record(_trace.LOG_LINE, replica=self.replica,
                                  msg=msg)
        self._write_wtime(msg)

    def leader_elected(self, term: int) -> None:
        """The exact greppable leader line of the reference
        (``"[T%d] LEADER"``, dare_server.c:1396, grepped by run.sh) —
        preserved byte-for-byte in the file; the structured twin is an
        ``election_win`` trace event + ``elections_won_total``
        counter."""
        if self.obs is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.trace.record(_trace.ELECTION_WIN,
                                  replica=self.replica, term=int(term))
            self.obs.metrics.inc("elections_won_total",
                                 replica=self.replica)
        # the trace above must not swallow the grep contract: the FILE
        # line below is what run.sh (and test_runtime_aux) greps
        self._write_wtime(f"[T{term}] LEADER")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class StepTimer:
    """rdtsc-style section timing (timer.h TIMER_START/STOP analog) with
    µs resolution, accumulated per label — and, when a registry is
    attached, observed into per-label ``timer_<label>_us`` histograms
    (per-replica labeled) so section timings export with every metrics
    snapshot instead of living only in ad-hoc report() strings."""

    # the shared µs ladder — spans sub-dispatch (~10µs) to
    # cold-compile stalls; one definition (obs.metrics) so timer
    # histograms stay comparable with the bench dispatch histograms
    BUCKETS_US = LATENCY_BUCKETS_US

    def __init__(self, metrics=None, replica: int = -1):
        self.acc = {}
        self._open = {}
        self.metrics = metrics    # MetricsRegistry or None
        self.replica = replica

    def start(self, label: str) -> None:
        self._open[label] = time.perf_counter_ns()

    def stop(self, label: str) -> None:
        t0 = self._open.pop(label, None)
        if t0 is not None:
            us = (time.perf_counter_ns() - t0) / 1e3
            n, tot, mx = self.acc.get(label, (0, 0.0, 0.0))
            self.acc[label] = (n + 1, tot + us, max(mx, us))
            if self.metrics is not None:
                self.metrics.observe(f"timer_{label}_us", us,
                                     buckets=self.BUCKETS_US,
                                     replica=self.replica)

    def report(self) -> str:
        lines = []
        for label, (n, tot, mx) in sorted(self.acc.items()):
            lines.append(f"{label}: n={n} mean={tot / max(n, 1):.1f}us "
                         f"max={mx:.1f}us")
        return "\n".join(lines)
