"""Silent-divergence auditing — cluster audit ledger, flight recorder,
audit artifacts, and the first-divergence CLI.

APUS's followers are passive in the replication hot path: one-sided
RDMA writes land in follower log memory with no receiver-side check,
so *silent state divergence* is a first-class failure mode of the
design ("The Impact of RDMA on Agreement", arXiv:1905.12143, makes the
same point about RDMA-written replica memory; "Reliable Replication
Protocols on SmartNICs", arXiv:2503.18093, argues offloaded
replication needs continuous end-to-end integrity checking). Our TPU
analog is identical — compiled step programs mutate replicated
Log/HardState pytrees with zero host-side verification. This module is
the *correctness observability* leg the metrics registry (PR 1) and
causal spans (PR 3) do not cover: proving, continuously and cheaply,
that R replicas (and G×R sharded replicas) hold bit-identical state at
matching ``(term, index)`` frontiers — and capturing enough recent
history to debug the step where they stopped.

Three parts, all host-side, stdlib+numpy only:

* :class:`AuditLedger` — consumes the on-device digest windows the
  compiled step emits under ``audit=True`` (one u32 mul-fold checksum
  per committed entry in ``[commit - W, commit)``, see
  ``consensus/step.py``), aligns them across replicas by **absolute**
  ``(group, term, index)`` (callers add their ``rebased_total`` so i32
  rollovers never tear the chain), tolerates frontier skew (each
  replica reports each index on its own schedule; comparison is
  per-index, not per-step), and raises a ``DIVERGENCE`` finding naming
  the first mismatching index. Two detection modes: a replica's first
  report of an index is cross-checked against the other replicas'
  digests, and every RE-report is checked against the replica's own
  previous window (vectorized numpy compare) — so post-commit bit
  corruption is caught even by a single-replica ledger (NodeDaemon).
* :class:`FlightRecorder` — a bounded ring of the last N step
  inputs/outputs + digest heads, dumped into a self-contained audit
  artifact when an alert fires, so the divergence window is
  inspectable (and, through the chaos reproducer it embeds into,
  replayable) after the fact.
* ``python -m rdma_paxos_tpu.obs.audit`` — merges per-replica dumps
  (each NodeDaemon only observes its own digests) and prints the
  first-divergence report; also reads audit artifacts and chaos
  reproducers that embed an audit dump.

HARD RULE (inherited from the rest of ``obs``): nothing here runs
inside jitted/``shard_map``ped code. The digest computation itself is
compiled — but only under the static ``audit=`` flag, cache-key
guarded so default programs stay byte-identical (tests/test_audit.py).
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys
import tempfile
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from rdma_paxos_tpu.config import DIGEST_EPOCH
from rdma_paxos_tpu.obs.clock import anchor as clock_anchor

# StepOutput fields emitted by the audit=True compiled step — the one
# list every host integration (SimCluster, ShardedCluster,
# HostReplicaDriver) extracts by
AUDIT_KEYS = ("audit_start", "audit_digest", "audit_term")

_SCHEMA = 1


def _mask_bits(mask: int) -> List[int]:
    return [i for i in range(mask.bit_length()) if (mask >> i) & 1]


def _finding_closed(f: dict, repairs: Sequence[dict]) -> bool:
    """A DIVERGENCE finding is closed only when EVERY replica on its
    diverging side has a covering repair record — a multi-replica
    finding (merge mode can name several holders of the same wrong
    digest) must not read 'repaired' after only one of them healed.
    'Covering' means the finding's index lies INSIDE the backfilled
    ``[lo, hi)`` range: an index below ``lo`` (the donor's ring had
    already pruned past it by repair time) was never re-verified, and
    closure is never claimed before it is proven — such a finding
    stays open (CLI exit 1) for the operator. A repair record closes
    only findings detected AT OR BEFORE it (step comparison, when
    both sides carry one): a stale record from an earlier incident
    must never close a LATER re-divergence it cannot have verified."""
    got = f.get("got_replicas", ())

    def covers(r):
        if r["group"] != f.get("group", 0):
            return False
        if not (r["lo"] <= f["index"] < r["hi"]):
            return False
        fs, rs = f.get("step"), r.get("step")
        return fs is None or rs is None or rs >= fs
    return bool(got) and all(
        any(covers(r) for r in repairs if r["replica"] == rr)
        for rr in got)


class AuditLedger:
    """Host-side digest ledger: per-index cross-replica comparison with
    bounded retention and exact first-divergence localization."""

    # findings are bounded too: a persistently corrupt replica would
    # otherwise grow findings/_flagged at commit throughput forever
    # (memory + lock-held summary scans + dump size) while the
    # operator responds to the page. The first MAX_FINDINGS localize
    # the divergence; further finding events only tick
    # ``findings_dropped`` (an EVENT count — post-cap re-reports of
    # the same index are no longer deduplicated, by design).
    MAX_FINDINGS = 256

    def __init__(self, n_replicas: int, n_groups: int = 1, *,
                 history: int = 4096, obs=None,
                 digest_epoch: int = DIGEST_EPOCH):
        self.R = int(n_replicas)
        self.G = int(n_groups)
        self.history = int(history)
        # digest LAYOUT version this ledger compares in
        # (config.DIGEST_EPOCH): windows/dumps stamped with a different
        # epoch are refused with an EPOCH_MISMATCH finding — digests
        # from different fold layouts are incomparable, not unequal
        self.digest_epoch = int(digest_epoch)
        # Observability facade for divergence counters/trace events;
        # may be (re)attached after construction — the engines assign
        # it lazily so driver-attached facades are picked up.
        self.obs = obs
        self._lock = threading.Lock()
        # per group: absolute index -> [term, digest, replica_bitmask]
        self._idx: List[Dict[int, list]] = [dict() for _ in range(self.G)]
        self._max: List[int] = [-1] * self.G
        # per (group, replica): last reported window, for the
        # vectorized self-recheck fast path
        self._lastwin: Dict[Tuple[int, int], tuple] = {}
        self._flagged: set = set()          # (group, index) reported once
        self._epoch_flagged: set = set()    # (group, replica, epoch)
        self.findings: List[dict] = []
        self.findings_dropped = 0           # events suppressed at cap
        self.windows = 0
        self.indices_checked = 0
        self.backfilled = 0                 # indices re-reported as backfill
        # completed repair records (mark_repaired): the audit loop's
        # closure evidence — rides dumps/merges so the CLI can verdict
        # "diverged but repaired + backfilled" with exit 0
        self.repairs: List[dict] = []

    # ---------------- recording ----------------

    def record_window(self, replica: int, start: int, digests, terms,
                      end: int, *, group: int = 0,
                      step: Optional[int] = None,
                      epoch: Optional[int] = None,
                      backfill: bool = False) -> None:
        """``digests``/``terms`` cover absolute indices ``[start,
        end)`` of ``replica``'s committed prefix (rebase-corrected by
        the caller). Re-reported indices are checked against the
        replica's previous window; first reports join the cross-replica
        store.

        ``epoch`` (when given) names the digest LAYOUT the window was
        computed under; a mismatch against this ledger's epoch is an
        ``EPOCH_MISMATCH`` finding and the window is refused — never
        compared, never a false ``DIVERGENCE`` (rolling digest-layout
        upgrades). ``backfill=True`` is the repair pipeline's history
        re-report (range re-digest): the frontier self-recheck is
        skipped — backfill windows arrive out of frontier order by
        design — and every index goes straight to the cross-replica
        store."""
        start, end = int(start), int(end)
        if epoch is not None and int(epoch) != self.digest_epoch:
            self._epoch_mismatch(group, replica, int(epoch), step)
            return
        if end <= start:
            return
        dig = np.asarray(digests)
        if dig.dtype != np.uint32:      # device emits u32; normalize
            dig = dig.astype(np.int64) & 0xFFFFFFFF
        trm = np.asarray(terms)
        with self._lock:
            self.windows += 1
            key = (group, replica)
            prev = None if backfill else self._lastwin.get(key)
            new_from = start
            if prev is not None:
                p_start, p_end, p_dig, p_trm = prev
                if start >= p_start and end >= p_end:
                    lo, hi = max(start, p_start), min(end, p_end)
                    if hi > lo:
                        a = dig[lo - start:hi - start]
                        b = p_dig[lo - p_start:hi - p_start]
                        # digest-only detection (the term column is
                        # FOLDED INTO the digest, so a term flip flips
                        # the digest too); terms are read back only to
                        # label the finding
                        if not np.array_equal(a, b):
                            j = int(np.argmax(a != b))
                            self._diverge(
                                group, lo + j, step, mode="self",
                                got=(int(trm[lo - start + j]),
                                     int(a[j])),
                                got_replicas=[replica],
                                expected=(int(p_trm[lo - p_start + j]),
                                          int(b[j])),
                                expected_replicas=[replica])
                        new_from = max(new_from, hi)
                # else: the window regressed (crash-restart recovery
                # re-reports a lower frontier) — fall through and
                # re-check every index against the cross-replica store
            if not backfill:
                self._lastwin[key] = (start, end, dig, trm)

            store = self._idx[group]
            bit = 1 << replica
            if new_from < end:
                # bulk-convert once: per-element numpy scalar indexing
                # in this loop was the dominant audit host cost
                new_t = trm[new_from - start:].tolist()
                new_d = dig[new_from - start:].tolist()
                for i, g_idx in enumerate(range(new_from, end)):
                    t, d = new_t[i], new_d[i]
                    ent = store.get(g_idx)
                    if ent is None:
                        store[g_idx] = [t, d, bit]
                    elif ent[0] == t and ent[1] == d:
                        ent[2] |= bit
                    else:
                        self._diverge(
                            group, g_idx, step, mode="replica",
                            got=(t, d), got_replicas=[replica],
                            expected=(ent[0], ent[1]),
                            expected_replicas=_mask_bits(ent[2]))
                        # the divergent replica's bit is deliberately
                        # NOT OR'd in: ent's mask means "replicas
                        # holding THIS digest" — polluting it would
                        # point dump/merge-based repair at the wrong
                        # replica set
                self.indices_checked += end - new_from
                if backfill:
                    self.backfilled += end - new_from
            if end - 1 > self._max[group]:
                self._max[group] = end - 1
            if len(store) > 2 * self.history:
                cut = self._max[group] - self.history
                for stale in [k for k in store if k < cut]:
                    del store[stale]

    def _diverge(self, group: int, index: int, step, *, mode: str,
                 got, got_replicas, expected, expected_replicas) -> None:
        fkey = (group, index)
        if fkey in self._flagged:
            return
        if len(self.findings) >= self.MAX_FINDINGS:
            self.findings_dropped += 1
            return
        self._flagged.add(fkey)
        finding = dict(
            type="DIVERGENCE", mode=mode, group=int(group),
            index=int(index), term=int(expected[0]),
            expected_digest=int(expected[1]),
            expected_replicas=list(expected_replicas),
            got_term=int(got[0]), got_digest=int(got[1]),
            got_replicas=list(got_replicas),
            step=(int(step) if step is not None else None))
        self.findings.append(finding)
        if self.obs is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.metrics.inc("audit_divergence_total", group=group)
            self.obs.trace.record(
                _trace.AUDIT_DIVERGENCE,
                **{k: v for k, v in finding.items() if k != "type"})

    def _epoch_mismatch(self, group: int, replica: int, epoch: int,
                        step) -> None:
        """A window computed under a DIFFERENT digest layout was
        offered: refuse comparison with a distinct finding (once per
        (group, replica, epoch)) — a layout upgrade in progress must
        never read as state divergence."""
        key = (int(group), int(replica), int(epoch))
        with self._lock:
            if key in self._epoch_flagged:
                return
            if len(self.findings) >= self.MAX_FINDINGS:
                self.findings_dropped += 1
                return
            self._epoch_flagged.add(key)
            finding = dict(
                type="EPOCH_MISMATCH", group=int(group), index=-1,
                replica=int(replica),
                expected_epoch=self.digest_epoch, got_epoch=int(epoch),
                step=(int(step) if step is not None else None))
            self.findings.append(finding)
        if self.obs is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.metrics.inc("audit_epoch_mismatch_total",
                                 group=group)
            self.obs.trace.record(
                _trace.AUDIT_EPOCH_MISMATCH,
                **{k: v for k, v in finding.items() if k != "type"})

    # ---------------- repair surface (runtime/repair.py) ----------------

    def digest_at(self, group: int, index: int) -> Optional[Tuple]:
        """``(term, digest, replica_bitmask)`` the store holds for the
        absolute ``index`` of ``group`` (the mask = replicas holding
        THIS digest), or None when not retained."""
        with self._lock:
            ent = self._idx[group].get(int(index))
            return None if ent is None else (int(ent[0]), int(ent[1]),
                                             int(ent[2]))

    def digest_range(self, group: int, lo: int,
                     hi: int) -> List[Optional[Tuple]]:
        """Bulk form of :meth:`digest_at` over absolute ``[lo, hi)``
        — ONE lock acquisition for the whole slice (snapshot
        verification walks up to n_slots indices per donor attempt;
        per-index locking would contend with the readback thread's
        live window recording for the entire walk)."""
        with self._lock:
            store = self._idx[group]
            return [
                (None if ent is None
                 else (int(ent[0]), int(ent[1]), int(ent[2])))
                for ent in (store.get(i)
                            for i in range(int(lo), int(hi)))]

    @property
    def majority(self) -> int:
        return self.R // 2 + 1

    def implicated_replicas(self, group: int = 0) -> set:
        """Replicas named on the DIVERGING side of any unrepaired
        DIVERGENCE finding of ``group`` — the minority set the repair
        pipeline quarantines, and the set donor selection must NEVER
        draw from."""
        with self._lock:
            out: set = set()
            for f in self.findings:
                if (f.get("type") == "DIVERGENCE"
                        and f["group"] == group
                        and not f.get("repaired")):
                    out.update(f["got_replicas"])
            return out

    def coverage(self, group: int, lo: int, hi: int) -> dict:
        """Audit coverage over absolute ``[lo, hi)`` of ``group``:
        ``ok`` iff every index is retained in the store AND held by a
        replica majority — the repair pipeline's 'fully audited again'
        acceptance check after a range-digest backfill."""
        lo, hi = int(lo), int(hi)
        maj = self.majority
        missing: List[int] = []
        minority: List[int] = []
        with self._lock:
            store = self._idx[group]
            for i in range(lo, hi):
                ent = store.get(i)
                if ent is None:
                    missing.append(i)
                elif bin(int(ent[2])).count("1") < maj:
                    minority.append(i)
        return dict(ok=not missing and not minority, lo=lo, hi=hi,
                    checked=hi - lo, missing=missing[:16],
                    non_majority=minority[:16])

    def reset_replica(self, group: int, replica: int) -> None:
        """Forget ``replica``'s last reported window (snapshot
        re-install rewrote its state: the next report legitimately
        disagrees with pre-repair memory and must not self-flag)."""
        with self._lock:
            self._lastwin.pop((group, replica), None)

    def mark_repaired(self, group: int, replica: int, lo: int, hi: int,
                      *, donor: int, index: int,
                      step: Optional[int] = None) -> dict:
        """Record a completed digest-verified repair of ``replica``
        (re-installed from ``donor``'s snapshot at determinant
        ``index``; ledger coverage backfilled over absolute ``[lo,
        hi)``) and mark every DIVERGENCE finding the repair covers
        ``repaired`` — the CLI report exits 0 once every divergence is
        repaired + backfilled."""
        rec = dict(group=int(group), replica=int(replica), lo=int(lo),
                   hi=int(hi), donor=int(donor), index=int(index),
                   step=(int(step) if step is not None else None))
        with self._lock:
            self.repairs.append(rec)
            for f in self.findings:
                if (f.get("type") == "DIVERGENCE"
                        and f["group"] == rec["group"]
                        and not f.get("repaired")
                        and _finding_closed(f, self.repairs)):
                    f["repaired"] = True
                    # re-arm detection at the closed index: the
                    # repaired replica holds NEW verified state there,
                    # so a LATER re-divergence (bad DRAM re-flipping
                    # the slot, a regressed-frontier re-report) must
                    # raise a fresh finding — not vanish into the
                    # dedup of a closed incident
                    self._flagged.discard((f["group"], f["index"]))
        return rec

    # ---------------- queries / export ----------------

    def first_divergence(self, group: Optional[int] = None
                         ) -> Optional[dict]:
        """The DIVERGENCE finding with the smallest ``(group, index)``
        — the first point the replicas stopped agreeing
        (EPOCH_MISMATCH findings are config refusals, not state
        divergence, and are excluded)."""
        cand = [f for f in self.findings
                if f.get("type", "DIVERGENCE") == "DIVERGENCE"
                and (group is None or f["group"] == group)]
        if not cand:
            return None
        return min(cand, key=lambda f: (f["group"], f["index"]))

    def summary(self) -> dict:
        """Deterministic (no wall clock) counters for health snapshots
        and chaos verdicts."""
        with self._lock:
            unrepaired = sum(
                1 for f in self.findings
                if f.get("type", "DIVERGENCE") != "DIVERGENCE"
                or not f.get("repaired"))
            return dict(
                n_replicas=self.R, n_groups=self.G,
                digest_epoch=self.digest_epoch,
                windows=self.windows,
                indices_checked=self.indices_checked,
                backfilled=self.backfilled,
                tracked=sum(len(s) for s in self._idx),
                findings=len(self.findings),
                findings_dropped=self.findings_dropped,
                repairs=len(self.repairs),
                unrepaired=unrepaired,
                first=self.first_divergence())

    def dump(self) -> dict:
        """Full ledger export: the retained per-index digest map (with
        replica masks) per group, plus every finding — the per-replica
        document the CLI merges across hosts."""
        with self._lock:
            groups = [dict(group=g, max_index=self._max[g],
                           indices={str(i): [int(e[0]), int(e[1]),
                                             int(e[2])]
                                    for i, e in sorted(
                                        self._idx[g].items())})
                      for g in range(self.G)]
            return dict(schema=_SCHEMA, kind="audit_ledger",
                        anchor=clock_anchor(),
                        n_replicas=self.R, n_groups=self.G,
                        digest_epoch=self.digest_epoch,
                        windows=self.windows,
                        indices_checked=self.indices_checked,
                        backfilled=self.backfilled,
                        findings=[dict(f) for f in self.findings],
                        findings_dropped=self.findings_dropped,
                        repairs=[dict(r) for r in self.repairs],
                        groups=groups)

    def write_json(self, path: str) -> str:
        """Atomic (tmp + rename) dump — the NodeDaemon's cadenced
        per-replica audit file."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.dump(), f, indent=2)
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def _to_plain(obj):
    """Recursive numpy/bytes→JSON conversion, applied at DUMP time
    only — the hot loop records raw arrays and payload bytes so a ring
    entry costs no per-value Python (measured: eager int/hex
    conversion was the dominant share of audit overhead)."""
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, (bytes, bytearray)):
        return obj.hex()
    if isinstance(obj, dict):
        return {k: _to_plain(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_plain(x) for x in obj]
    return obj


class FlightRecorder:
    """Bounded ring of the last N step records (inputs, outputs, digest
    heads) — the evidence window an audit artifact ships when an alert
    fires. Entry values may be numpy arrays/scalars; conversion to
    plain JSON data happens at :meth:`dump`, never in the record path.
    The ring holds the most recent ``capacity`` entries."""

    def __init__(self, capacity: int = 64):
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, entry: dict) -> None:
        with self._lock:
            self._ring.append(entry)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def dump(self) -> dict:
        with self._lock:
            steps = [_to_plain(e) for e in self._ring]
        return dict(schema=_SCHEMA, kind="flight",
                    capacity=self.capacity, anchor=clock_anchor(),
                    steps=steps)


# ---------------------------------------------------------------------------
# audit artifacts (chaos/artifact.py conventions: one atomic JSON with
# everything a post-mortem needs)
# ---------------------------------------------------------------------------

def write_audit_artifact(path: Optional[str] = None, *, reason: str,
                         ledger: Optional[AuditLedger] = None,
                         flight: Optional[FlightRecorder] = None,
                         obs=None, config: Optional[dict] = None,
                         extra: Optional[dict] = None) -> str:
    """Persist a self-contained audit artifact (atomic tmp + rename):
    ledger dump + flight-recorder ring + obs trace/metrics. Returns
    the path (auto-generated under the system temp dir when None)."""
    doc = dict(
        schema=_SCHEMA, kind="audit_artifact", reason=reason,
        anchor=clock_anchor(), config=config or {},
        audit=(ledger.dump() if ledger is not None else None),
        flight=(flight.dump() if flight is not None else None),
        trace=(obs.trace.dump() if obs is not None else None),
        metrics=(obs.metrics.snapshot() if obs is not None else None),
        extra=extra or {},
    )
    if path is None:
        fd, path = tempfile.mkstemp(prefix="audit_dump_", suffix=".json")
        os.close(fd)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# merge + first-divergence report (multi-host dumps)
# ---------------------------------------------------------------------------

def _as_ledger_dumps(doc: dict, source: str) -> List[dict]:
    """Normalize any supported document into ledger-dump dicts: a raw
    AuditLedger dump, an audit artifact, or a chaos reproducer with an
    embedded audit dump."""
    if doc.get("kind") == "audit_ledger" or "groups" in doc:
        return [doc]
    if doc.get("kind") == "audit_artifact" and doc.get("audit"):
        return [doc["audit"]]
    if isinstance(doc.get("extra"), dict) and doc["extra"].get("audit"):
        return [doc["extra"]["audit"]]
    raise SystemExit(f"{source}: not an audit dump, audit artifact, or "
                     "reproducer with an embedded audit dump")


def merge_dumps(dumps: Sequence[dict]) -> dict:
    """Merge per-replica ledger dumps (e.g. one per NodeDaemon) into
    one report: each host's own findings are unioned (a ``repaired``
    flag from ANY dump wins — repair closure propagates), then shared
    absolute indices are cross-compared ACROSS dumps — the multi-host
    equivalent of the in-process ledger's cross-replica check.

    Dumps stamped with DIFFERENT digest-layout epochs are never
    cross-compared: the comparison runs within each epoch cohort, and
    one ``EPOCH_MISMATCH`` finding names the epochs seen (a rolling
    layout upgrade must read as 'incomparable', never as a false
    DIVERGENCE)."""
    findings: List[dict] = []
    flagged: Dict[tuple, dict] = {}
    repairs: List[dict] = []
    for doc in dumps:
        for f in doc.get("findings", []):
            # the union key carries the detection step too: a closed
            # incident and a LATER re-divergence at the same index are
            # distinct findings and must both survive the merge
            k = (f.get("type", "DIVERGENCE"), f.get("group", 0),
                 f["index"], f.get("step"))
            prev = flagged.get(k)
            if prev is None:
                prev = dict(f)
                flagged[k] = prev
                findings.append(prev)
            elif f.get("repaired") and not prev.get("repaired"):
                prev["repaired"] = True
        for r in doc.get("repairs", []):
            repairs.append(dict(r))
    # repair records from any dump close matching findings everywhere
    # — every replica on the diverging side must be covered, so a
    # multi-replica merge finding stays open until ALL of them healed
    for f in findings:
        if f.get("type", "DIVERGENCE") != "DIVERGENCE" \
                or f.get("repaired"):
            continue
        if _finding_closed(f, repairs):
            f["repaired"] = True
    # indices already carrying a host-reported DIVERGENCE finding —
    # the cross-dump comparison must not duplicate them
    seen_idx = {(f.get("group", 0), f["index"]) for f in findings
                if f.get("type", "DIVERGENCE") == "DIVERGENCE"}
    epochs = sorted({int(doc.get("digest_epoch", DIGEST_EPOCH))
                     for doc in dumps})
    if len(epochs) > 1:
        findings.append(dict(
            type="EPOCH_MISMATCH", group=-1, index=-1, replica=-1,
            expected_epoch=epochs[0], got_epoch=epochs[-1],
            epochs=epochs, step=None))
    indices = 0
    for epoch in epochs:
        cohort = [doc for doc in dumps
                  if int(doc.get("digest_epoch", DIGEST_EPOCH))
                  == epoch]
        by_group: Dict[int, Dict[int, list]] = {}
        for doc in cohort:
            for gdoc in doc.get("groups", []):
                tgt = by_group.setdefault(int(gdoc["group"]), {})
                for idx, (t, d, m) in gdoc["indices"].items():
                    tgt.setdefault(int(idx), []).append(
                        (int(t), int(d), int(m)))
        for g, idxmap in sorted(by_group.items()):
            for i, rows in sorted(idxmap.items()):
                indices += 1
                if len({(t, d) for (t, d, _m) in rows}) > 1 \
                        and (g, i) not in seen_idx:
                    exp = rows[0]
                    bad = next(r for r in rows
                               if (r[0], r[1]) != (exp[0], exp[1]))
                    f = dict(
                        type="DIVERGENCE", mode="merge", group=g,
                        index=i, term=exp[0], expected_digest=exp[1],
                        expected_replicas=_mask_bits(exp[2]),
                        got_term=bad[0], got_digest=bad[1],
                        got_replicas=_mask_bits(bad[2]), step=None)
                    seen_idx.add((g, i))
                    findings.append(f)
    # DIVERGENCE findings first (EPOCH_MISMATCH carries index -1 and
    # must not shadow the first real divergence)
    findings.sort(key=lambda f: (f.get("type", "DIVERGENCE")
                                 != "DIVERGENCE",
                                 f.get("group", 0), f["index"]))
    unrepaired = [f for f in findings
                  if f.get("type", "DIVERGENCE") != "DIVERGENCE"
                  or not f.get("repaired")]
    return dict(schema=_SCHEMA, kind="audit_report", dumps=len(dumps),
                indices=indices, findings=findings, repairs=repairs,
                unrepaired=len(unrepaired),
                first=(findings[0] if findings else None))


def format_report(report: dict) -> str:
    lines = [f"audit report: {report['dumps']} dump(s), "
             f"{report['indices']} indices compared, "
             f"{len(report['findings'])} finding(s)"]
    first = report.get("first")
    if first is None:
        lines.append("no divergence: all reported digests agree")
    elif first.get("type", "DIVERGENCE") != "DIVERGENCE":
        lines.append(
            "EPOCH MISMATCH: digest layout epochs %s are incomparable "
            "— finish the rolling digest upgrade before comparing"
            % (first.get("epochs",
                         [first.get("expected_epoch"),
                          first.get("got_epoch")]),))
    else:
        lines.append(
            "FIRST DIVERGENCE: group %d index %d term %d — expected "
            "digest 0x%08x (replicas %s) got 0x%08x (term %d, replicas "
            "%s) [%s]%s" % (
                first.get("group", 0), first["index"], first["term"],
                first["expected_digest"], first["expected_replicas"],
                first["got_digest"], first["got_term"],
                first["got_replicas"], first.get("mode", "?"),
                " — REPAIRED" if first.get("repaired") else ""))
        for f in report["findings"][1:6]:
            if f.get("type", "DIVERGENCE") != "DIVERGENCE":
                continue
            lines.append("  also: group %d index %d (0x%08x vs 0x%08x)%s"
                         % (f.get("group", 0), f["index"],
                            f["expected_digest"], f["got_digest"],
                            " — repaired" if f.get("repaired") else ""))
        if len(report["findings"]) > 6:
            lines.append(f"  ... {len(report['findings']) - 6} more")
    # repair-status section: the self-healing loop's closure evidence
    repairs = report.get("repairs", [])
    if repairs:
        lines.append("repair status: %d repair(s), %d unrepaired "
                     "finding(s)" % (len(repairs),
                                     report.get("unrepaired", 0)))
        for r in repairs[:8]:
            lines.append(
                "  repaired: group %d replica %d re-installed from "
                "donor %d at index %d, backfilled [%d, %d)%s"
                % (r["group"], r["replica"], r["donor"], r["index"],
                   r["lo"], r["hi"],
                   (" @ step %d" % r["step"])
                   if r.get("step") is not None else ""))
        if report.get("unrepaired", 0) == 0 and report["findings"]:
            lines.append("  all divergences repaired + backfilled "
                         "(exit 0)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _load(paths: Sequence[str]) -> List[dict]:
    dumps: List[dict] = []
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        dumps.extend(_as_ledger_dumps(doc, p))
    return dumps


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rdma_paxos_tpu.obs.audit",
        description="Merge per-replica audit dumps and print the "
                    "first-divergence report.")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("report", help="print the merged "
                        "first-divergence report (exit 1 on divergence)")
    rp.add_argument("files", nargs="+",
                    help="audit dumps / audit artifacts / reproducers")
    mp = sub.add_parser("merge", help="write the merged report JSON")
    mp.add_argument("files", nargs="+")
    mp.add_argument("-o", "--out", required=True)
    args = ap.parse_args(argv)

    report = merge_dumps(_load(args.files))
    if args.cmd == "merge":
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
        print(f"wrote {args.out}: {len(report['findings'])} finding(s) "
              f"over {report['indices']} indices from "
              f"{report['dumps']} dump(s)")
    else:
        print(format_report(report))
    # a past divergence that is marked repaired + backfilled is a
    # CLOSED incident: the report exits clean (the self-healing loop's
    # CI contract); anything unrepaired — or any epoch mismatch —
    # still fails the check
    return 1 if report["unrepaired"] else 0


if __name__ == "__main__":
    sys.exit(main())
