"""``python -m rdma_paxos_tpu.obs`` — the unified trace-plane CLI.

Two commands over any mix of dump files (raw span dumps, subsystem
trace dumps, combined ``Observability.snapshot()`` documents, or whole
postmortem bundles — inputs are classified by shape, so you can point
either command at whatever a chaos run or ``console bundle`` left
behind):

* ``merge`` — one Perfetto-loadable Chrome trace JSON with command
  spans AND subsystem traces (txn / topology / watch) on the shared
  clock, cross-host dumps aligned by their ``(monotonic, wall)``
  anchors.
* ``blame`` — the critical-path blame report: per-command latency
  decomposed into admission / txn_lock / topology_freeze / dispatch /
  quorum / apply / ack, with the dominant phase named per percentile.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence

from rdma_paxos_tpu.obs.tracectx import blame, format_blame, merge_timeline


def _classify(doc, span_dumps: List[dict],
              trace_dumps: List[dict]) -> None:
    """Sort a loaded JSON document into span dumps and subsystem trace
    dumps by shape — lists are raw dumps, dicts are containers
    (snapshots nest dumps under the same keys; bundles nest whole
    documents under ``sections``)."""
    if not isinstance(doc, dict):
        return
    sections = doc.get("sections")
    if isinstance(sections, dict):
        for v in sections.values():
            if isinstance(v, list):
                for item in v:
                    _classify(item, span_dumps, trace_dumps)
            else:
                _classify(v, span_dumps, trace_dumps)
        return
    spans = doc.get("spans")
    if isinstance(spans, list):
        span_dumps.append(doc)
    elif isinstance(spans, dict):
        _classify(spans, span_dumps, trace_dumps)
    traces = doc.get("traces")
    if isinstance(traces, list):
        trace_dumps.append(doc)
    elif isinstance(traces, dict):
        _classify(traces, span_dumps, trace_dumps)


def _load(paths: Sequence[str]):
    span_dumps: List[dict] = []
    trace_dumps: List[dict] = []
    for p in paths:
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise SystemExit(f"{p}: {e}")
        _classify(doc, span_dumps, trace_dumps)
    return span_dumps, trace_dumps


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rdma_paxos_tpu.obs",
        description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="merge span dumps + subsystem "
                        "trace dumps into ONE Perfetto-loadable "
                        "Chrome trace on the shared clock")
    mp.add_argument("files", nargs="+", help="span/trace/snapshot/"
                    "bundle JSONs")
    mp.add_argument("-o", "--out", required=True,
                    help="Chrome trace JSON output path")
    bp = sub.add_parser("blame", help="print the critical-path blame "
                        "report (phase shares + dominant phase per "
                        "latency percentile)")
    bp.add_argument("files", nargs="+")
    bp.add_argument("--json", action="store_true",
                    help="emit the raw report document instead of the "
                    "table")
    args = ap.parse_args(argv)

    span_dumps, trace_dumps = _load(args.files)
    if not span_dumps and not trace_dumps:
        raise SystemExit("no span or trace dumps found in the inputs "
                         "(need 'spans' or 'traces' keys)")
    if args.cmd == "merge":
        doc = merge_timeline(span_dumps, trace_dumps)
        with open(args.out, "w") as f:
            json.dump(doc, f)
        print(f"wrote {args.out}: {len(doc['traceEvents'])} events "
              f"({doc['otherData']['spans']} spans, "
              f"{doc['otherData']['traces']} subsystem traces) — load "
              f"it in https://ui.perfetto.dev")
    else:
        doc = blame(span_dumps, trace_dumps)
        if args.json:
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print(format_blame(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
