"""Protocol trace ring — a bounded in-memory ring of typed protocol
events with monotonic timestamps, dumpable on failure or on demand.

Per-replica text logs (``utils/debug.py``, the ``debug.h`` analog) are
the greppable operator surface; this ring is the STRUCTURED one: every
protocol-level transition (election start/win, step batch sizes, commit
index advance, rebase applied/stalled, snapshot taken/installed,
membership change, proxy event enqueue / ack release) is recorded as a
typed event the harness can assert on and a failure handler can dump as
JSON. Bounded (deque ``maxlen``) so a hot loop can record freely — the
ring holds the most recent window, which is exactly what a post-mortem
wants.

Host-side only: nothing here may run inside jitted/``shard_map``ped
code (see ``tests/test_obs.py``).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import List, NamedTuple, Optional

# ---------------------------------------------------------------------------
# event kinds (typed protocol events)
# ---------------------------------------------------------------------------

ELECTION_START = "election_start"        # timeout fired / deliberate depose
ELECTION_WIN = "election_win"            # became_leader (the LEADER line)
STEP_BATCH = "step_batch"                # leader appended a batch
COMMIT_ADVANCE = "commit_advance"        # commit index moved
REBASE_APPLIED = "rebase_applied"        # coordinated i32 rollover ran
REBASE_STALLED = "rebase_stalled"        # end past threshold, delta pinned 0
SNAPSHOT_TAKEN = "snapshot_taken"        # donor snapshot captured
SNAPSHOT_INSTALLED = "snapshot_installed"  # snapshot installed into replica
CHECKPOINT_TAKEN = "checkpoint_taken"    # app-state checkpoint + compaction
MEMBERSHIP_CHANGE = "membership_change"  # CONFIG transit/stable/eviction
PROXY_ENQUEUE = "proxy_enqueue"          # shim event queued for consensus
PROXY_ACK_RELEASE = "proxy_ack_release"  # commit released blocked waiters
INFLIGHT_FAILED = "inflight_failed"      # waiters failed (-1)
STEP_DOWN = "step_down"                  # lost-majority step-down
QUIESCE_UNKNOWN = "quiesce_unknown"      # kernel-queue barrier unverifiable
GENERATION_CUT = "generation_cut"        # elastic world cut
GENERATION_BREAK = "generation_break"    # elastic world broken
STOP_FORCED = "stop_forced"              # stop() with a wedged poll thread
LOG_LINE = "log"                         # routed ReplicaLog event line
FAULT_INJECTED = "fault_injected"        # chaos nemesis fault applied
CRASH_RESTART = "crash_restart"          # chaos crash-restart recovery ran
NEMESIS_VIOLATION = "nemesis_violation"  # chaos invariant/linearize failure
AUDIT_DIVERGENCE = "audit_divergence"    # digest mismatch at (term, index)
AUDIT_DUMPED = "audit_dumped"            # audit artifact written
AUDIT_EPOCH_MISMATCH = "audit_epoch_mismatch"  # incomparable digest layout
REPLICA_QUARANTINED = "replica_quarantined"  # diverged minority isolated
REPAIR_DONOR_REJECTED = "repair_donor_rejected"  # donor failed digest verify
REPAIR_INSTALLED = "repair_installed"    # digest-verified snapshot re-install
REPAIR_BACKFILLED = "repair_backfilled"  # range re-digest restored coverage
REPAIR_READMITTED = "repair_readmitted"  # probation passed; serving again
REPAIR_ESCALATED = "repair_escalated"    # bounded retries exhausted (page)
ALERT_FIRED = "alert_fired"              # SLO alert rule started firing
ALERT_RESOLVED = "alert_resolved"        # SLO alert rule stopped firing
LEASE_GRANTED = "lease_granted"          # leader lease activated
LEASE_RENEWED = "lease_renewed"          # verified-quorum renewal (sampled)
LEASE_EXPIRED = "lease_expired"          # validity lapsed (no fresh quorum)
LEASE_REVOKED = "lease_revoked"          # deposed / quarantined / stepped down
GOVERNOR_TIER = "governor_tier"          # dispatch tier changed
GOVERNOR_SHED = "governor_shed"          # SLO burn pager dropped tier to serial
GOVERNOR_RESUME = "governor_resume"      # shed latch cleared (pager resolved)
IDLE_QUIESCE = "idle_quiesce"            # poll loop entered idle quiescence
TOPOLOGY_PROPOSED = "topology_proposed"  # policy proposed a split/merge
TOPOLOGY_SEEDED = "topology_seeded"      # migrating range copied to targets
TOPOLOGY_VERIFIED = "topology_verified"  # range digests matched pre-cutover
TOPOLOGY_FROZEN = "topology_frozen"      # migrating-range writes queued
TOPOLOGY_CUTOVER = "topology_cutover"    # router swapped, epoch bumped
TOPOLOGY_DONE = "topology_done"          # transition window closed
TOPOLOGY_ABANDONED = "topology_abandoned"  # window gave up (deadline)


class TraceEvent(NamedTuple):
    seq: int          # global monotone order within this ring
    ts: float         # time.monotonic() at record
    kind: str
    replica: int      # -1 when not replica-scoped
    fields: dict

    def as_dict(self) -> dict:
        # fields first so a field that collides with a header key
        # (seq/ts/kind/replica) can never shadow the header — the
        # header is the record's identity
        out = dict(self.fields)
        out.update(seq=self.seq, ts=self.ts, kind=self.kind,
                   replica=self.replica)
        return out


class TraceRing:
    """Bounded, ordered, thread-safe ring of :class:`TraceEvent`."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, kind: str, replica: int = -1,
               **fields) -> TraceEvent:
        with self._lock:
            self._seq += 1
            ev = TraceEvent(self._seq, time.monotonic(), kind, replica,
                            fields)
            self._ring.append(ev)
        return ev

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def events(self, kind: Optional[str] = None,
               replica: Optional[int] = None) -> List[TraceEvent]:
        """Snapshot of retained events, oldest first, optionally
        filtered by kind and/or replica."""
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e.kind == kind]
        if replica is not None:
            evs = [e for e in evs if e.replica == replica]
        return evs

    def dump(self) -> List[dict]:
        return [e.as_dict() for e in self.events()]

    def dump_json(self, reason: Optional[str] = None,
                  indent: Optional[int] = None) -> str:
        # every event ts is time.monotonic(); the stamped anchor pair
        # (obs.clock) lets readers project them onto the shared wall
        # timebase and align this dump with health/span exports
        from rdma_paxos_tpu.obs.clock import anchor
        return json.dumps(dict(reason=reason, capacity=self.capacity,
                               anchor=anchor(), events=self.dump()),
                          indent=indent)

    def dump_on_failure(self, path: str, reason: str) -> str:
        """Persist the ring (atomic tmp + rename) for post-mortem —
        called from failure paths (poll-loop crash, wedged stop) and on
        demand. Returns ``path``."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.dump_json(reason=reason, indent=2))
        os.replace(tmp, path)
        return path

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


# process-global default — sink for module-level instrumentation with
# no driver instance in scope (snapshot.py, elastic.py, proxy quiesce)
_default = TraceRing()


def default_ring() -> TraceRing:
    return _default
