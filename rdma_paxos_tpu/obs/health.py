"""Health reporter — periodic per-replica health snapshots as JSON files.

The drivers build one small dict per replica each reporting period
(role, term, commit/apply indices, log headroom against the i32 rebase
ceiling, inflight waiter count, stable-store progress) and this module
writes each atomically (tmp + rename, never fsynced — loss only costs
one period) to ``<workdir>/replica<r>.health.json``, where an operator,
the bench harness, or a supervising process can poll them without
touching the driver. ``ClusterDriver.health()`` aggregates the same
dicts live.

Schema: every snapshot carries at least :data:`HEALTH_FIELDS`; extra
keys (store stats, rebase counters) ride along freely.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

# the required schema — tests and aggregators key off these
HEALTH_FIELDS = (
    "replica", "role", "term", "leader_id",
    "commit", "apply", "end", "head",
    "log_headroom",          # rebase_threshold - end (i32 ceiling margin)
    "inflight",              # blocked commit waiters
    "ts",                    # time.time() at snapshot
)

# the CLUSTER-level schema (``ClusterDriver.health()`` /
# ``ShardedClusterDriver.health()``): every field the subsystems of
# PRs 5-10 now emit — alert firing state, audit summary + artifact
# path, repair pipeline status, lease/read-path status. Values may be
# None (e.g. ``audit`` on an unaudited cluster) but the KEYS must be
# present, so aggregators (the fleet console, the bundle assembler)
# never have to feature-probe a health document.
CLUSTER_HEALTH_FIELDS = (
    "n_replicas", "replicas",
    "alerts",                # AlertEngine.state() (since/duration_s)
    "audit",                 # AuditLedger.summary() or None
    "audit_artifact",        # last dumped artifact path or None
    "repair",                # RepairController.status() or None
    "leases",                # LeaseManager.status() or None
    "reads",                 # ReadHub.status() or None
    "streams",               # StreamHub.status() or None
    "txn",                   # TxnCoordinator.health() or None
    "blame",                 # tracectx.health_blame() or None
    "ts",
)


def validate(snap: dict) -> List[str]:
    """-> the list of required fields missing from ``snap`` (empty when
    the snapshot conforms)."""
    return [f for f in HEALTH_FIELDS if f not in snap]


def validate_cluster(snap: dict) -> List[str]:
    """Cluster-health schema check: the :data:`CLUSTER_HEALTH_FIELDS`
    keys plus a leader view — ``leader`` (single-group) or
    ``leaders`` (one per group, sharded). Returns the missing field
    names (empty when the document conforms)."""
    missing = [f for f in CLUSTER_HEALTH_FIELDS if f not in snap]
    if "leader" not in snap and "leaders" not in snap:
        missing.append("leader|leaders")
    return missing


def make_cluster_snapshot(**fields) -> dict:
    """Stamp cluster-level health ``fields`` with the same
    schema/clock headers :func:`make_snapshot` gives per-replica
    snapshots (wall + monotonic + the shared anchor pair), so a saved
    ``health()`` document merges onto the fleet timebase like every
    other dump."""
    from rdma_paxos_tpu.obs.clock import anchor
    snap = dict(schema=2, ts=time.time(),
                ts_monotonic=time.monotonic(), anchor=anchor())
    snap.update(fields)
    return snap


def make_snapshot(**fields) -> dict:
    """Stamp ``fields`` into a schema-versioned snapshot dict. Carries
    both clocks — ``ts`` (wall, operator-meaningful) and
    ``ts_monotonic`` (ordering-safe) — plus the process's shared
    ``(monotonic, wall)`` anchor pair (obs.clock), so health files
    align on the same timebase as trace-ring and span dumps."""
    from rdma_paxos_tpu.obs.clock import anchor
    snap = dict(schema=1, ts=time.time(), ts_monotonic=time.monotonic(),
                anchor=anchor())
    snap.update(fields)
    return snap


class HealthReporter:
    """Cadenced atomic per-replica JSON writer + reader."""

    def __init__(self, workdir: str, period: float = 0.5,
                 clock=time.monotonic):
        self.workdir = workdir
        self.period = period
        self._clock = clock
        self._last = float("-inf")

    def path(self, replica: int) -> str:
        return os.path.join(self.workdir, f"replica{replica}.health.json")

    def due(self) -> bool:
        return self._clock() - self._last >= self.period

    def write(self, snaps: Dict[int, dict]) -> None:
        """Write every replica's snapshot atomically and reset the
        cadence clock. Atomic against process death (tmp + rename); NOT
        fsynced — a power loss costs at most one period's snapshot,
        which the next period rewrites."""
        for r, snap in snaps.items():
            path = self.path(r)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f, indent=2)
            os.replace(tmp, path)
        self._last = self._clock()

    def maybe_write(self, snaps: Dict[int, dict]) -> bool:
        """Cadenced write; returns True if a write happened."""
        if not self.due():
            return False
        self.write(snaps)
        return True

    def cluster_path(self) -> str:
        return os.path.join(self.workdir, "cluster.health.json")

    def write_cluster(self, doc: dict) -> None:
        """Atomic write of the CLUSTER-level health document
        (``make_cluster_snapshot`` shape) next to the per-replica
        files — the file-based fleet console and the postmortem
        bundle's alert-state source read it."""
        path = self.cluster_path()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, path)

    def read(self, replica: int) -> Optional[dict]:
        try:
            with open(self.path(replica)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def read_all(self, n_replicas: int) -> List[Optional[dict]]:
        return [self.read(r) for r in range(n_replicas)]
