"""Metrics exposition — Prometheus text rendering + the ops HTTP
exporter.

Everything the obs stack knows is, until this module, reachable only
from INSIDE the process (``driver.health()``) or post-hoc from dump
files. The exporter opens the standard pull surface an operator (or a
Prometheus scraper, or the fleet console) points at from OUTSIDE:

* ``/metrics`` — the registry in Prometheus text format v0.0.4
  (counters/gauges as-is, histograms as cumulative ``_bucket{le=}`` +
  ``_sum`` + ``_count``).
* ``/metrics.json`` — the raw registry ``snapshot()`` (the bundle's
  telemetry section; every ``device_*`` series rides here).
* ``/healthz`` — the attached ``health_fn()`` as JSON; HTTP 503 when
  the health document carries a truthy ``loop_error`` (a dead poll
  loop must fail the probe, not smile through it).
* ``/series`` — the attached :class:`~rdma_paxos_tpu.obs.series.
  TimeSeriesStore` retained state.
* ``/alerts`` — the attached ``AlertEngine`` per-rule state + the
  currently-firing list.

Deliberately boring transport: stdlib ``ThreadingHTTPServer`` bound to
localhost, ``port=0`` = OS-assigned ephemeral (the tests' and benches'
mode), serving threads are daemons. The exporter runs BESIDE the
drivers' readback thread and touches only thread-safe read surfaces
(registry snapshot, engine state, series rings, ``health()``) — it is
never on the dispatch path, and attaching it changes no compiled
program and no STEP_CACHE key (tests/test_ops_plane.py pins both).

Stdlib only, host-side only (jit-safety-scanned).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from rdma_paxos_tpu.obs.metrics import parse_key as _split

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _prom_labels(pairs, extra: str = "") -> str:
    parts = [f'{_prom_name(k)}="{_escape(v)}"' for k, v in pairs]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _escape(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def render_prometheus(snap: dict) -> str:
    """Render a registry ``snapshot()`` dict as Prometheus text
    exposition format v0.0.4. Histogram buckets become CUMULATIVE
    ``le=`` counts (the registry stores per-bucket counts). All
    samples of one metric family are emitted as one uninterrupted
    group under one ``# TYPE`` header (a format MUST — enforced here
    by grouping rather than trusting input ordering, so any snapshot
    dict renders validly)."""
    families: dict = {}     # base -> (kind, [sample lines])

    def fam(base: str, kind: str):
        return families.setdefault(base, (kind, []))[1]

    for key, v in snap["counters"].items():
        base, pairs = _split(key)
        base = _prom_name(base)
        fam(base, "counter").append(f"{base}{_prom_labels(pairs)} {v}")
    for key, v in snap["gauges"].items():
        base, pairs = _split(key)
        base = _prom_name(base)
        fam(base, "gauge").append(f"{base}{_prom_labels(pairs)} {v}")
    for key, h in snap["histograms"].items():
        base, pairs = _split(key)
        base = _prom_name(base)
        out = fam(base, "histogram")
        ex = h.get("exemplars") or {}

        def tail(bound: str) -> str:
            # OpenMetrics exemplar syntax: append the bucket's most
            # recent sampled trace to its `_bucket` line. Absent
            # exemplars leave the v0.0.4 line byte-identical.
            res = ex.get(bound)
            if not res:
                return ""
            tid, v = res[-1]
            return f' # {{trace_id="{_escape(tid)}"}} {v}'

        cum = 0
        for bound, c in h["buckets"].items():
            if bound == "+Inf":
                continue
            cum += c
            le = _prom_labels(pairs, extra=f'le="{bound}"')
            out.append(f"{base}_bucket{le} {cum}{tail(bound)}")
        inf = _prom_labels(pairs, extra='le="+Inf"')
        out.append(f"{base}_bucket{inf} {h['count']}{tail('+Inf')}")
        out.append(f"{base}_sum{_prom_labels(pairs)} {h['sum']}")
        out.append(f"{base}_count{_prom_labels(pairs)} {h['count']}")
    lines = []
    for base in sorted(families):
        kind, samples = families[base]
        lines.append(f"# TYPE {base} {kind}")
        lines.extend(samples)
    return "\n".join(lines) + "\n"


class OpsExporter:
    """Opt-in localhost HTTP exposition of one process's ops plane
    (registry / health / series / alerts). ``port=0`` binds an
    OS-assigned ephemeral port — read it back from :attr:`port`."""

    def __init__(self, *, registry,
                 health_fn: Optional[Callable[[], dict]] = None,
                 alerts=None, series=None,
                 host: str = "127.0.0.1", port: int = 0):
        self.registry = registry
        self.health_fn = health_fn
        self.alerts = alerts
        self.series = series
        self._thread: Optional[threading.Thread] = None
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):    # noqa: N802 — stdlib name
                pass                      # never spam the serving logs

            def _reply(self, code: int, body: bytes,
                       ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, doc, code: int = 200) -> None:
                self._reply(code, json.dumps(doc).encode(),
                            "application/json")

            def do_GET(self):             # noqa: N802 — stdlib name
                try:
                    exporter._route(self)
                except BrokenPipeError:
                    pass                  # client went away mid-write
                except Exception as exc:  # noqa: BLE001 — the probe
                    # surface must answer, never kill its own thread
                    try:
                        self._json(dict(error=repr(exc)), code=500)
                    except OSError:
                        pass

        self._server = ThreadingHTTPServer((host, int(port)), Handler)
        self._server.daemon_threads = True

    # one routing table, testable without sockets
    def _route(self, h) -> None:
        path = h.path.split("?", 1)[0]
        if path == "/metrics":
            h._reply(200, render_prometheus(
                self.registry.snapshot()).encode(),
                "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/metrics.json":
            h._json(self.registry.snapshot())
        elif path == "/healthz":
            if self.health_fn is None:
                h._json(dict(ok=True))
                return
            doc = self.health_fn()
            h._json(doc, code=503 if doc.get("loop_error") else 200)
        elif path == "/series":
            if self.series is None:
                h._json(dict(error="no series store attached"), 404)
            else:
                h._json(self.series.to_dict())
        elif path == "/alerts":
            if self.alerts is None:
                h._json(dict(error="no alert engine attached"), 404)
            else:
                h._json(dict(state=self.alerts.state(),
                             firing=self.alerts.firing()))
        else:
            h._json(dict(error=f"unknown path {path!r}",
                         endpoints=["/metrics", "/metrics.json",
                                    "/healthz", "/series",
                                    "/alerts"]), 404)

    # ---------------- lifecycle ----------------

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "OpsExporter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="ops-exporter", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        t, self._thread = self._thread, None
        if t is not None:
            self._server.shutdown()
            t.join(timeout=5.0)
        self._server.server_close()
