"""One causal trace plane — cross-subsystem provenance on the shared
clock.

:mod:`~rdma_paxos_tpu.obs.spans` follows ONE consensus command; this
module links what happens *around* commands into the same timeline:

* :class:`TraceContext` — a thread-safe, bounded store of subsystem
  traces. A trace is a named interval with ordered **phases** (the
  txn coordinator's lock-wait → prepare → vote-wait → decide chain, a
  topology window's seed → freeze → verify → cutover chain, a watch
  delivery's pump → deliver chain), **links** to the `(conn, req)`
  span keys of the consensus records it fanned out, a **parent**
  pointer for blame ("this txn aborted because THAT transition window
  froze its range"), and free-form attrs. Trace ids are deterministic
  (`kind-N` from a per-kind counter) so chaos runs replay
  bit-identically under a scripted clock.

* :func:`merge_timeline` — folds span dumps AND trace dumps into one
  Perfetto-loadable Chrome trace JSON: replica tracks + critical-path
  tracks from :func:`~rdma_paxos_tpu.obs.spans.to_chrome_trace`, plus
  one pseudo-process per subsystem (txn / topology / watch) whose
  tracks carry the phase slices. Everything aligns on the shared
  :mod:`~rdma_paxos_tpu.obs.clock` anchors, so cross-host dumps merge
  the same way span dumps always have.

* :func:`blame` — the critical-path blame report: decomposes each
  sampled command's latency into admission / txn-lock /
  topology-freeze / dispatch / quorum / apply / ack and names the
  dominant phase per percentile. `txn-lock` comes from a linked txn
  trace's lock-wait phase; `topology-freeze` is the span's overlap
  with any transition window's freeze→cutover interval — the two
  components no single-subsystem view can see.

HARD RULE (inherited from the rest of ``obs``): host-side only. No
call site lives inside jitted/mapped step code; enabling tracing
changes no compiled programs and no step outputs. An unsampled
command costs one counter increment (its subsystem never calls in:
:func:`active_tracer` gates on the same sampling switch the span
recorder uses).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

from rdma_paxos_tpu.obs.clock import anchor as clock_anchor
from rdma_paxos_tpu.obs.spans import (
    ACK, APPEND, APPLY, CP_PHASES, ENQUEUE, QUORUM, SUBMIT,
    to_chrome_trace)

DEFAULT_CAPACITY = 1024

# subsystem pseudo-processes on the merged timeline (below the span
# exporter's CP_PID=9999 / READS_PID=9998)
SUBSYS_PIDS = {"txn": 9997, "topology": 9996, "watch": 9995}
OTHER_SUBSYS_PID = 9990

# the blame decomposition, in report order (also the dominance
# tie-break order: earlier wins a tie)
BLAME_PHASES = ("admission", "txn_lock", "topology_freeze",
                "dispatch", "quorum", "apply", "ack")


class _Trace:
    """One subsystem trace (host bookkeeping only)."""

    __slots__ = ("tid", "kind", "parent", "status", "t0", "t1",
                 "phases", "links", "attrs")

    def __init__(self, tid: str, kind: str, parent: Optional[str],
                 t0: float, attrs: dict):
        self.tid = tid
        self.kind = kind
        self.parent = parent
        self.status = "open"
        self.t0 = t0
        self.t1: Optional[float] = None
        self.phases: List[List] = []       # [name, ts] in call order
        self.links: List[List[int]] = []   # [conn, req, group]
        self.attrs: dict = dict(attrs)

    def as_dict(self) -> dict:
        return dict(tid=self.tid, kind=self.kind, parent=self.parent,
                    status=self.status, t0=self.t0, t1=self.t1,
                    phases=[list(p) for p in self.phases],
                    links=[list(l) for l in self.links],
                    attrs=dict(self.attrs))


class TraceContext:
    """Thread-safe, bounded store of cross-subsystem traces.

    Ids are deterministic (``kind-N``) so two chaos runs of the same
    seed under a scripted clock dump byte-identical timelines. The
    store is leaf-locked: every method takes only ``_lock`` and calls
    nothing that locks, so producers may call in while holding their
    own subsystem locks (the txn coordinator and topology controller
    both do) without lock-order hazards.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock=time.monotonic):
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        # guarded-by: _lock [writes]
        self._open: Dict[str, _Trace] = {}
        # guarded-by: _lock [writes]
        self._done: collections.deque = collections.deque(
            maxlen=self.capacity)
        # guarded-by: _lock [writes] — per-kind id counters
        self._seq: Dict[str, int] = {}
        self.dropped = 0                   # evicted-while-open count
        from rdma_paxos_tpu.analysis import runtime_guard
        runtime_guard.maybe_guard(self, "_lock", __file__)

    def now(self) -> float:
        """The context's clock — producers that backdate a trace start
        (e.g. the watch hub stamping commit time at kick) read it here
        so every timestamp in one dump shares a timebase."""
        return self._clock()

    @property
    def open_count(self) -> int:
        return len(self._open)

    # ---------------- recording ----------------

    def begin(self, kind: str, parent: Optional[str] = None,
              ts: Optional[float] = None, **attrs) -> str:
        """Open a trace; returns its deterministic id (``kind-N``)."""
        with self._lock:
            n = self._seq.get(kind, 0)
            self._seq[kind] = n + 1
            tid = f"{kind}-{n}"
            if len(self._open) >= self.capacity:
                # evict the oldest open trace (a leaked/abandoned one)
                # rather than refusing new work forever
                old = next(iter(self._open))
                self._end_locked(self._open[old], "evicted",
                                 self._clock())
                self.dropped += 1
            tr = _Trace(tid, kind, parent,
                        self._clock() if ts is None else float(ts),
                        attrs)
            self._open[tid] = tr
            return tid

    def phase(self, tid: str, name: str, ts: Optional[float] = None,
              once: bool = False) -> None:
        """Stamp a named phase start on an open trace (no-op on an
        unknown/ended id). ``once=True`` dedupes: a driver loop that
        re-enters the same controller state each tick records the
        phase only the first time."""
        with self._lock:
            tr = self._open.get(tid)
            if tr is None:
                return
            if once and any(p[0] == name for p in tr.phases):
                return
            tr.phases.append(
                [name, self._clock() if ts is None else float(ts)])

    def annotate(self, tid: str, **attrs) -> None:
        with self._lock:
            tr = self._open.get(tid)
            if tr is not None:
                tr.attrs.update(attrs)

    def link(self, tid: str, conn: int, req: int,
             group: int = -1) -> None:
        """Link a consensus record's span key ``(conn, req)`` (and its
        group) to this trace — the join column the blame report and
        the merged timeline use."""
        with self._lock:
            tr = self._open.get(tid)
            if tr is not None:
                tr.links.append([int(conn), int(req), int(group)])

    def set_parent(self, tid: str, parent: Optional[str]) -> None:
        """Late-bind the blocking parent (e.g. a TOPOLOGY-aborted txn
        learns its transition window only at abort time)."""
        with self._lock:
            tr = self._open.get(tid)
            if tr is not None:
                tr.parent = parent

    def end(self, tid: str, status: str = "done",
            ts: Optional[float] = None, **attrs) -> None:
        with self._lock:
            tr = self._open.get(tid)
            if tr is None:
                return
            if attrs:
                tr.attrs.update(attrs)
            self._end_locked(tr, status,
                             self._clock() if ts is None else float(ts))

    # holds-lock: _lock
    def _end_locked(self, tr: _Trace, status: str, t1: float) -> None:
        tr.status = status
        tr.t1 = t1
        self._open.pop(tr.tid, None)
        self._done.append(tr)

    def fail_open(self, status: str = "failover") -> int:
        """Terminate EVERY open trace (process stop / driver crash):
        the trace-plane analogue of ``SpanRecorder.fail_open`` — open
        traces must terminate, never leak. Returns the count."""
        n = 0
        with self._lock:
            ts = self._clock()
            for tr in list(self._open.values()):
                self._end_locked(tr, status, ts)
                n += 1
        return n

    # ---------------- queries / export ----------------

    def get(self, tid: str) -> Optional[dict]:
        with self._lock:
            tr = self._open.get(tid)
            if tr is not None:
                return tr.as_dict()
            for done in self._done:
                if done.tid == tid:
                    return done.as_dict()
        return None

    def counts(self) -> dict:
        with self._lock:
            by_kind: Dict[str, int] = {}
            for tr in self._done:
                by_kind[tr.kind] = by_kind.get(tr.kind, 0) + 1
            return dict(open=len(self._open), done=len(self._done),
                        dropped=self.dropped, by_kind=by_kind)

    def dump(self, anchor: Optional[dict] = None) -> dict:
        """Point-in-time trace dump, stamped with the shared clock
        anchor — merges with span dumps from any process on one
        timebase. Open traces are included as-is (status ``open``)."""
        with self._lock:
            traces = ([tr.as_dict() for tr in self._done]
                      + [tr.as_dict() for tr in self._open.values()])
        return dict(schema=1,
                    anchor=anchor if anchor is not None
                    else clock_anchor(),
                    dropped=self.dropped, traces=traces)

    def write_json(self, path: str) -> str:
        import json
        import os
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.dump(), f, indent=2)
        os.replace(tmp, path)
        return path

    def reset(self) -> None:
        with self._lock:
            self._open.clear()
            self._done.clear()
            self._seq.clear()
            self.dropped = 0


def active_tracer(obs) -> Optional[TraceContext]:
    """The facade's trace context iff tracing is enabled — gated on
    the SAME sampling switch as :func:`active_recorder`, so an
    operator who turns spans off (``RP_TRACE_SAMPLE=0``) silences the
    whole trace plane with it and an unsampled deployment pays one
    counter increment per command, nothing more."""
    if obs is None:
        return None
    tc = getattr(obs, "tracectx", None)
    if tc is None:
        return None
    sp = getattr(obs, "spans", None)
    return tc if (sp is not None and sp.enabled) else None


# ---------------------------------------------------------------------------
# merged Perfetto timeline (spans + subsystem traces)
# ---------------------------------------------------------------------------

def _wall_fn(dump: dict):
    a = dump["anchor"]

    def wall(ts, _a=a):
        return _a["wall"] + (ts - _a["monotonic"])

    return wall


def _as_list(dumps) -> List[dict]:
    if dumps is None:
        return []
    if isinstance(dumps, dict):
        return [dumps]
    return list(dumps)


def merge_timeline(span_dumps, trace_dumps=(), *,
                   t0_wall: Optional[float] = None) -> dict:
    """Merge span dumps AND trace dumps into ONE Perfetto-loadable
    Chrome trace JSON: the span exporter's replica / critical-path /
    reads tracks, plus one pseudo-process per subsystem kind whose
    tracks carry each trace as an outer slice with nested phase
    slices. All dumps align via their stamped clock anchors; the
    timeline epoch is the min wall timestamp across BOTH planes (or
    ``t0_wall`` when given), so a txn trace, its prepare-record spans,
    the transition window that aborted it, and the watch delivery of
    the commit all land on the same axis."""
    span_dumps = _as_list(span_dumps)
    trace_dumps = _as_list(trace_dumps)
    walls: List[float] = []
    for d in span_dumps:
        wall = _wall_fn(d)
        for sp in d["spans"]:
            walls.extend(wall(ts) for _, _, ts in sp["events"])
        for rd in d.get("reads", ()):
            walls.append(wall(rd["t0"]))
    prepared = []
    for d in trace_dumps:
        wall = _wall_fn(d)
        for tr in d["traces"]:
            walls.append(wall(tr["t0"]))
        prepared.append((d, wall))
    t0 = (t0_wall if t0_wall is not None
          else (min(walls) if walls else 0.0))
    out = to_chrome_trace(span_dumps, t0_wall=t0)
    events = out["traceEvents"]

    def us(w):
        return round((w - t0) * 1e6, 3)

    tids: Dict[int, int] = {}              # pid -> next track id
    pids_seen: Dict[int, str] = {}
    n_traces = 0
    for d, wall in prepared:
        for tr in d["traces"]:
            n_traces += 1
            pid = SUBSYS_PIDS.get(tr["kind"], OTHER_SUBSYS_PID)
            pids_seen.setdefault(
                pid, tr["kind"] if pid != OTHER_SUBSYS_PID
                else "subsystem")
            tid = tids.get(pid, 0) + 1
            tids[pid] = tid
            ta = wall(tr["t0"])
            # an open trace renders up to its last known timestamp
            tz = tr["t1"] if tr["t1"] is not None else (
                tr["phases"][-1][1] if tr["phases"] else tr["t0"])
            tb = wall(tz)
            args = dict(trace=tr["tid"], kind=tr["kind"],
                        status=tr["status"], parent=tr["parent"],
                        links=[f"c{c}/r{r}" for c, r, _ in tr["links"]])
            args.update(tr["attrs"])
            events.append(dict(
                name="thread_name", ph="M", pid=pid, tid=tid,
                args=dict(name=f"{tr['tid']} [{tr['status']}]")))
            events.append(dict(
                name=f"{tr['tid']} [{tr['status']}]", ph="X",
                ts=us(ta), dur=round(max(tb - ta, 0.0) * 1e6, 3),
                pid=pid, tid=tid, args=args))
            # nested phase slices: each named phase runs from its
            # stamp to the next phase's stamp (or trace end)
            bounds = [wall(ts) for _, ts in tr["phases"]] + [tb]
            for (name, _), pa, pb in zip(tr["phases"], bounds,
                                         bounds[1:]):
                events.append(dict(
                    name=name, ph="X", ts=us(pa),
                    dur=round(max(pb - pa, 0.0) * 1e6, 3),
                    pid=pid, tid=tid, args=dict(trace=tr["tid"])))
    for pid in sorted(pids_seen):
        events.append(dict(name="process_name", ph="M", pid=pid,
                           tid=0, args=dict(name=pids_seen[pid])))
    out["otherData"]["traces"] = n_traces
    return out


# ---------------------------------------------------------------------------
# critical-path blame
# ---------------------------------------------------------------------------

def _span_marks(sp: dict, wall) -> Dict[str, float]:
    marks: Dict[str, float] = {}
    for phase, rep, ts in sp["events"]:
        if phase not in CP_PHASES:
            continue
        if phase == APPLY and rep != sp["origin"] and APPLY in marks:
            continue
        if phase in marks and phase != APPLY:
            continue
        marks[phase] = wall(ts)
    return marks


def blame(span_dumps, trace_dumps=()) -> dict:
    """Decompose per-command latency into the BLAME_PHASES components
    and name the dominant phase per percentile.

    Pure-span components come from a span's own phase marks
    (admission = submit→enqueue, dispatch = →append, quorum =
    →quorum, apply = →apply, ack = →ack); `txn_lock` is the lock-wait
    of a txn trace that LINKS the span's ``(conn, req)`` key;
    `topology_freeze` is the span's overlap with any topology trace's
    freeze→cutover window. The command total is its span extent plus
    its txn lock-wait (the wait precedes submit — invisible to the
    span, real to the client)."""
    span_dumps = _as_list(span_dumps)
    trace_dumps = _as_list(trace_dumps)
    # (conn, req) -> lock-wait seconds, from txn traces
    lock_wait: Dict[Tuple[int, int], float] = {}
    # [t_freeze_wall, t_end_wall) transition windows
    windows: List[Tuple[float, float]] = []
    for d in trace_dumps:
        wall = _wall_fn(d)
        for tr in d["traces"]:
            ph = {name: wall(ts) for name, ts in tr["phases"]}
            if tr["kind"] == "txn" and "lock_wait" in ph:
                until = ph.get("prepare", ph.get("merge"))
                if until is None and tr["t1"] is not None:
                    until = wall(tr["t1"])
                if until is not None and until > ph["lock_wait"]:
                    w = until - ph["lock_wait"]
                    for conn, req, _ in tr["links"]:
                        lock_wait[(conn, req)] = w
            elif tr["kind"] == "topology" and "freeze" in ph:
                end = ph.get("cutover")
                if end is None and tr["t1"] is not None:
                    end = wall(tr["t1"])
                if end is not None and end > ph["freeze"]:
                    windows.append((ph["freeze"], end))
    rows: List[Tuple[float, Dict[str, float]]] = []
    for d in span_dumps:
        wall = _wall_fn(d)
        for sp in d["spans"]:
            marks = _span_marks(sp, wall)
            chain = [(p, marks[p]) for p in CP_PHASES if p in marks]
            if len(chain) < 2:
                continue
            comp: Dict[str, float] = {}

            def _seg(name, a, b):
                if a in marks and b in marks and marks[b] > marks[a]:
                    comp[name] = comp.get(name, 0.0) + (
                        marks[b] - marks[a])

            _seg("admission", SUBMIT, ENQUEUE)
            if ENQUEUE in marks:
                _seg("dispatch", ENQUEUE, APPEND)
            else:
                _seg("dispatch", SUBMIT, APPEND)
            _seg("quorum", APPEND, QUORUM)
            _seg("apply", QUORUM, APPLY)
            _seg("ack", APPLY, ACK)
            lw = lock_wait.get((sp["conn"], sp["req"]))
            if lw:
                comp["txn_lock"] = lw
            a, b = chain[0][1], chain[-1][1]
            frozen = sum(max(0.0, min(b, w1) - max(a, w0))
                         for w0, w1 in windows)
            if frozen > 0:
                comp["topology_freeze"] = frozen
            total = (b - a) + comp.get("txn_lock", 0.0)
            if total > 0:
                rows.append((total, comp))
    doc = dict(commands=len(rows), phases={}, percentiles={})
    if not rows:
        return doc
    grand = sum(t for t, _ in rows)
    agg: Dict[str, List[float]] = {}
    for _, comp in rows:
        for name, v in comp.items():
            agg.setdefault(name, []).append(v)
    for name in BLAME_PHASES:
        vals = agg.get(name)
        if not vals:
            continue
        tot = sum(vals)
        doc["phases"][name] = dict(
            n=len(vals), total_us=round(tot * 1e6, 1),
            mean_us=round(tot / len(vals) * 1e6, 1),
            max_us=round(max(vals) * 1e6, 1),
            share=round(tot / grand, 4) if grand else 0.0)
    rows.sort(key=lambda r: r[0])
    n = len(rows)
    for pname, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
        total, comp = rows[min(int(n * q), n - 1)]
        dom, dv = None, -1.0
        for name in BLAME_PHASES:
            v = comp.get(name, 0.0)
            if v > dv:
                dom, dv = name, v
        doc["percentiles"][pname] = dict(
            latency_us=round(total * 1e6, 1), dominant=dom,
            components={name: round(comp[name] * 1e6, 1)
                        for name in BLAME_PHASES if name in comp})
    return doc


def format_blame(doc: dict) -> str:
    lines = [f"commands: {doc['commands']}"]
    if not doc["commands"]:
        return lines[0] + " (nothing sampled)"
    width = max(len(p) for p in BLAME_PHASES)
    lines.append(f"{'phase'.ljust(width)}  {'n':>7} {'total_us':>12} "
                 f"{'mean_us':>10} {'max_us':>10} {'share':>7}")
    for name in BLAME_PHASES:
        st = doc["phases"].get(name)
        if st is None:
            continue
        lines.append(f"{name.ljust(width)}  {st['n']:>7} "
                     f"{st['total_us']:>12.1f} {st['mean_us']:>10.1f} "
                     f"{st['max_us']:>10.1f} {st['share']:>7.1%}")
    for pname in ("p50", "p95", "p99"):
        pe = doc["percentiles"].get(pname)
        if pe is None:
            continue
        parts = " ".join(f"{k}={v:.1f}us"
                         for k, v in pe["components"].items())
        lines.append(f"{pname}: {pe['latency_us']:.1f}us dominated by "
                     f"{pe['dominant']} ({parts})")
    return "\n".join(lines)


def blame_summary(doc: dict) -> Optional[dict]:
    """Compact per-percentile dominant-phase summary for health
    snapshots / the console BLAME column."""
    if not doc.get("commands"):
        return None
    out = {p: doc["percentiles"][p]["dominant"]
           for p in ("p50", "p95", "p99")
           if p in doc["percentiles"]}
    if "p99" in doc["percentiles"]:
        out["p99_us"] = doc["percentiles"]["p99"]["latency_us"]
    return out or None


def health_blame(obs) -> Optional[dict]:
    """The one-liner the drivers embed in health snapshots: blame over
    the process's own live span/trace dumps, or None when tracing is
    off / nothing sampled yet."""
    rec = getattr(obs, "spans", None) if obs is not None else None
    if rec is None or not rec.enabled:
        return None
    sd = rec.dump()
    if not sd["spans"]:
        return None
    tc = getattr(obs, "tracectx", None)
    tds = [tc.dump()] if tc is not None else []
    return blame_summary(blame([sd], tds))
