"""Device telemetry — on-device protocol counters, profiler-correlated
dispatch timelines, and per-variant compiled-program cost reports.

Every observability layer before this one stops at the dispatch
boundary: the jit-safety rule keeps metrics/trace/span calls out of
compiled code, so the step program is a black box — elections,
quorum widths, link-model drops, and log occupancy are only ever
*inferred* from host-side outputs, and the one device-time signal
(``fence=``) perturbs the very pipeline it measures. Replication
offload work makes the same point (PAPERS.md: SmartNIC replication,
arXiv:2503.18093; RDMA agreement, arXiv:1905.12143): once the protocol
hot path moves off the host, the telemetry must move with it. Three
legs, mirroring that split:

* **On-device counters** (``telemetry=True`` compiled steps,
  ``consensus/step.py``): a compact u32 vector per replica per step —
  elections started, votes granted/denied, appends accepted,
  commit-frontier advance, link-model drops consumed, effective
  quorum width, log headroom — reduced in-program so readback is
  O(counters), never O(log). The engines ingest the vector on the
  PR 6 readback thread (``finish``) into the metrics registry as
  ``device_*{replica=,group=}`` series and into a host accumulator
  (:func:`zeros` / :func:`accumulate`) tests can assert exactly.
  ``telemetry=False`` programs and STEP_CACHE keys are bit-identical
  to the pre-telemetry world (cache-key guarded like ``fence=`` and
  ``audit=``; ``tests/test_device_obs.py``).

* **:class:`ProfilerSession`** — a bounded ``jax.profiler`` capture
  manager (driver API / ``run_bench --profile`` / alert-triggered).
  The profiler's Chrome-trace output stamps event ``ts`` as
  microseconds since ``start_trace``; the session records
  ``time.time()`` immediately before starting, so device events
  project onto the shared :mod:`~rdma_paxos_tpu.obs.clock` wall
  timebase exactly — :func:`merge_timeline` folds them into the span
  export as one Perfetto document: client span → host phases →
  actual device execution.

* **:func:`program_report`** — per-STEP_CACHE-variant compiled-program
  cost: ``lowered.compile().cost_analysis()`` flops / bytes accessed
  plus ``memory_analysis()`` argument/output/temp sizes, emitted as a
  ``program_report.json`` artifact and a BENCH row.

Layout contract: :data:`COUNTERS` + :data:`GAUGES` name the vector
columns IN ORDER. ``consensus/step.py`` carries its own matching
``T_*`` index constants — it must NOT import this module (the static
jit-safety scan pins profiler/registry symbols unreachable from
compiled code); ``tests/test_device_obs.py`` pins the two layouts
against each other instead.

HARD RULE (inherited from the rest of ``obs``): nothing here runs
inside jitted/``shard_map``ped code. JAX is imported lazily (profiler
and program-report paths only) so the module stays importable from
any host layer.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from rdma_paxos_tpu.obs.clock import anchor as clock_anchor

# ---------------------------------------------------------------------------
# counter-vector layout (mirrors consensus/step.py T_* — pinned by test)
# ---------------------------------------------------------------------------

# monotone per-step counts: accumulated (summed) across steps/bursts
COUNTERS = (
    "elections_started",    # this replica began a candidacy
    "votes_granted",        # granted another replica's candidacy
    "votes_denied",         # heard candidacies it did not grant
    "accepted_entries",     # client entries appended from the batch
    "committed_entries",    # commit-frontier advance
    "links_unheard",        # peers masked by partition/link model
)
# point-in-time values: latest step wins (min across a fused burst
# for log_headroom — the tightest the ring got inside the dispatch)
GAUGES = (
    "quorum_width",         # replicas that acked this replica's window
    "log_headroom",         # free ring slots: (n_slots-1) - (end-head)
)
NAMES: Tuple[str, ...] = COUNTERS + GAUGES
WIDTH = len(NAMES)
INDEX: Dict[str, int] = {n: i for i, n in enumerate(NAMES)}

_N_COUNTERS = len(COUNTERS)
_I_QUORUM = INDEX["quorum_width"]
_I_HEADROOM = INDEX["log_headroom"]


def zeros(*lead_shape: int) -> np.ndarray:
    """The host-side telemetry accumulator: int64 ``[..., WIDTH]``."""
    return np.zeros(tuple(lead_shape) + (WIDTH,), np.int64)


def reduce_steps(stacked: np.ndarray) -> np.ndarray:
    """Reduce a fused burst's per-step vectors ``[K, ..., WIDTH]`` to
    one ``[..., WIDTH]`` vector: counters sum over the K steps,
    ``quorum_width`` takes the final step's value, ``log_headroom``
    the minimum across the burst (the tightest the ring got)."""
    out = stacked.sum(axis=0).astype(np.int64)
    out[..., _I_QUORUM] = stacked[-1, ..., _I_QUORUM]
    out[..., _I_HEADROOM] = stacked[..., _I_HEADROOM].min(axis=0)
    return out


def accumulate(acc: np.ndarray, vec: np.ndarray) -> np.ndarray:
    """Fold one finish()'s reduced vector into the running host
    accumulator: counter columns add, gauge columns overwrite."""
    acc[..., :_N_COUNTERS] += vec[..., :_N_COUNTERS]
    acc[..., _N_COUNTERS:] = vec[..., _N_COUNTERS:]
    return acc


def export(metrics, vec: np.ndarray, *, replica: int,
           group: Optional[int] = None) -> None:
    """Push one replica's reduced vector into the registry:
    ``device_<counter>_total`` counters (incremented by this finish's
    delta) and ``device_<gauge>`` gauges, labelled ``{replica=}`` (+
    ``{group=}`` for sharded engines). Host-side only — runs on the
    readback thread, never inside compiled code."""
    labels = dict(replica=replica)
    if group is not None:
        labels["group"] = group
    for i, name in enumerate(COUNTERS):
        v = int(vec[i])
        if v:
            metrics.inc("device_%s_total" % name, v, **labels)
    for name in GAUGES:
        metrics.set("device_%s" % name, int(vec[INDEX[name]]), **labels)


def ingest(obs, vec: np.ndarray, *, group_offset: int = 0) -> None:
    """Registry export for a whole reduced vector array: ``[R, WIDTH]``
    (single group) or ``[G, R, WIDTH]`` (sharded — ``group_offset``
    shifts the group label for multi-host shards)."""
    if obs is None:
        return
    m = obs.metrics
    if vec.ndim == 2:
        for r in range(vec.shape[0]):
            export(m, vec[r], replica=r)
    else:
        for g in range(vec.shape[0]):
            for r in range(vec.shape[1]):
                export(m, vec[g, r], replica=r, group=g + group_offset)


# ---------------------------------------------------------------------------
# jax.profiler capture manager
# ---------------------------------------------------------------------------

# jax.profiler allows ONE active trace per process; the session guards
# that invariant so driver/CLI/alert triggers can race benignly
_ACTIVE_LOCK = threading.Lock()
_ACTIVE: Optional["ProfilerSession"] = None


class ProfilerSession:
    """A bounded ``jax.profiler`` capture whose device trace aligns
    onto the shared obs wall timebase.

    The profiler's Chrome-trace output stamps event ``ts`` in
    microseconds since the ``start_trace`` call, so the session
    records ``time.time()`` immediately before starting:
    ``wall = wall_start + ts * 1e-6`` projects every device event onto
    the same timebase span dumps use (:mod:`obs.clock`). ``stop()`` is
    explicit; :meth:`maybe_stop` enforces ``max_seconds`` from a host
    poll loop (the driver calls it each observe pass) so an
    alert-triggered capture can never run unbounded."""

    def __init__(self, log_dir: str, *, max_seconds: float = 10.0):
        self.log_dir = log_dir
        self.max_seconds = float(max_seconds)
        self.active = False
        self.wall_start: Optional[float] = None
        self.anchor = None
        self.trace_files: List[str] = []
        self._deadline = float("inf")

    def start(self) -> "ProfilerSession":
        global _ACTIVE
        import jax
        with _ACTIVE_LOCK:
            if _ACTIVE is not None and _ACTIVE.active:
                raise RuntimeError(
                    "a ProfilerSession is already active (jax allows "
                    "one trace per process); stop it first")
            os.makedirs(self.log_dir, exist_ok=True)
            self.anchor = clock_anchor()
            self.wall_start = time.time()
            self._deadline = time.monotonic() + self.max_seconds
            jax.profiler.start_trace(self.log_dir)
            self.active = True
            _ACTIVE = self
        return self

    def expired(self) -> bool:
        return self.active and time.monotonic() >= self._deadline

    def maybe_stop(self) -> bool:
        """Stop iff the bounded duration elapsed (poll-loop hook)."""
        if self.expired():
            self.stop()
            return True
        return False

    def stop(self) -> "ProfilerSession":
        global _ACTIVE
        with _ACTIVE_LOCK:
            if not self.active:
                return self
            import jax
            try:
                jax.profiler.stop_trace()
            finally:
                # even when trace serialization fails (disk full in
                # log_dir), the session must read inactive and release
                # the one-per-process slot — otherwise every later
                # maybe_stop/start_profile retries against a wedged
                # trace instead of reporting this one's error
                self.active = False
                if _ACTIVE is self:
                    _ACTIVE = None
            # resolve INSIDE the lock: a concurrent stop() returns on
            # the not-active fast path above only after the files are
            # populated, so its caller never reads an empty capture
            self.trace_files = sorted(glob.glob(
                os.path.join(self.log_dir, "**", "*.trace.json.gz"),
                recursive=True))
        return self

    def chrome_events(self) -> List[dict]:
        """The captured raw Chrome trace events (``ts`` µs since
        :attr:`wall_start`), concatenated across trace files. Empty
        when the capture produced none (or was never stopped)."""
        events: List[dict] = []
        for path in self.trace_files:
            with gzip.open(path, "rt") as f:
                doc = json.load(f)
            events.extend(doc.get("traceEvents", []))
        return events

    def summary(self) -> dict:
        return dict(log_dir=self.log_dir, active=self.active,
                    wall_start=self.wall_start,
                    max_seconds=self.max_seconds,
                    trace_files=list(self.trace_files))


def load_profiler_dir(log_dir: str) -> List[dict]:
    """Raw Chrome events from a previously captured profiler log dir
    (the CLI path — no live session needed)."""
    s = ProfilerSession(log_dir)
    s.trace_files = sorted(glob.glob(
        os.path.join(log_dir, "**", "*.trace.json.gz"), recursive=True))
    return s.chrome_events()


# ---------------------------------------------------------------------------
# merged Perfetto timeline: spans + host phases + device trace
# ---------------------------------------------------------------------------

HOST_PHASE_PID = 9998        # one below the spans critical-path pid
DEVICE_PID_BASE = 10000      # profiler pids are remapped above here
# a busy capture emits millions of runtime events; an uncapped merge
# writes a multi-hundred-MB JSON no viewer loads. The newest events
# (the serving window, not the capture-init preamble) are kept; the
# drop count lands in otherData — bounded, never silently complete.
MAX_DEVICE_EVENTS = 200_000


def _span_walls(dumps: Sequence[dict]) -> List[float]:
    walls: List[float] = []
    for d in dumps:
        a = d["anchor"]
        for sp in d["spans"]:
            walls.extend(a["wall"] + (ts - a["monotonic"])
                         for _, _, ts in sp["events"])
    return walls


def merge_timeline(span_dumps, *, phase_events: Optional[Sequence] = None,
                   phase_anchor: Optional[dict] = None,
                   profiler: Optional[ProfilerSession] = None,
                   device_events: Optional[Sequence[dict]] = None,
                   device_wall_start: Optional[float] = None,
                   max_cp_tracks: int = 512,
                   max_device_events: int = MAX_DEVICE_EVENTS) -> dict:
    """One Perfetto document on ONE wall timebase: the span export's
    replica + critical-path tracks, a ``host phases`` track from the
    :class:`~rdma_paxos_tpu.obs.spans.StepPhaseProfiler` event ring
    (``(phase, t0_monotonic, t1_monotonic)`` triples projected through
    ``phase_anchor``), and the profiler's device-execution tracks
    (``ts`` µs since the capture's ``wall_start``). Every source
    contributes to the common epoch, so the three layers line up —
    a client span's quorum wait sits directly above the host dispatch
    phase and the device program that served it."""
    from rdma_paxos_tpu.obs import spans as spans_mod

    if isinstance(span_dumps, dict):
        span_dumps = [span_dumps]
    span_dumps = list(span_dumps or [])
    phase_events = list(phase_events or [])
    if profiler is not None:
        device_events = profiler.chrome_events()
        device_wall_start = profiler.wall_start
    device_events = [e for e in (device_events or [])
                     if e.get("ph") in ("X", "M")]

    pa = phase_anchor if phase_anchor is not None else clock_anchor()
    walls = _span_walls(span_dumps)
    walls.extend(pa["wall"] + (t0 - pa["monotonic"])
                 for _, t0, _ in phase_events)
    if device_events and device_wall_start is not None:
        walls.append(device_wall_start)
    t0_wall = min(walls) if walls else 0.0

    doc = spans_mod.to_chrome_trace(span_dumps, t0_wall=t0_wall,
                                    max_cp_tracks=max_cp_tracks)
    events = doc["traceEvents"]

    def us(w: float) -> float:
        return round((w - t0_wall) * 1e6, 3)

    # host-phase track: one thread row per phase name
    if phase_events:
        tids = {p: i + 1
                for i, p in enumerate(sorted({p for p, _, _
                                              in phase_events}))}
        events.append(dict(name="process_name", ph="M",
                           pid=HOST_PHASE_PID, tid=0,
                           args=dict(name="host phases")))
        for p, tid in sorted(tids.items()):
            events.append(dict(name="thread_name", ph="M",
                               pid=HOST_PHASE_PID, tid=tid,
                               args=dict(name=p)))
        for p, m0, m1 in phase_events:
            w0 = pa["wall"] + (m0 - pa["monotonic"])
            w1 = pa["wall"] + (m1 - pa["monotonic"])
            events.append(dict(
                name=p, ph="X", ts=us(w0),
                dur=round(max(w1 - w0, 0.0) * 1e6, 3),
                pid=HOST_PHASE_PID, tid=tids[p], args={}))

    # device tracks: profiler pids remapped above DEVICE_PID_BASE so
    # they can never collide with replica / critical-path / phase pids
    n_dev = 0
    dev_dropped = 0
    if device_events and device_wall_start is not None:
        xs = [e for e in device_events if e.get("ph") == "X"]
        if len(xs) > max_device_events:
            # keep the NEWEST slices (the serving window) and say so.
            # Chrome traces are ordered per thread/file, NOT globally
            # by time — sort first or the tail-slice drops whole
            # device tracks instead of the capture-init preamble
            xs.sort(key=lambda e: e.get("ts", 0))
            dev_dropped = len(xs) - max_device_events
            keep = xs[-max_device_events:]
            device_events = ([e for e in device_events
                              if e.get("ph") == "M"] + keep)
        pid_map: Dict[int, int] = {}
        for e in device_events:
            pid = pid_map.setdefault(
                e.get("pid", 0), DEVICE_PID_BASE + len(pid_map))
            ne = dict(e)
            ne["pid"] = pid
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    ne["args"] = dict(name="device: %s"
                                      % e.get("args", {}).get("name", "?"))
                events.append(ne)
                continue
            ne["ts"] = us(device_wall_start + e["ts"] * 1e-6)
            events.append(ne)
            n_dev += 1

    doc["otherData"]["merged"] = True
    doc["otherData"]["host_phase_events"] = len(phase_events)
    doc["otherData"]["device_events"] = n_dev
    doc["otherData"]["device_events_dropped"] = dev_dropped
    return doc


# ---------------------------------------------------------------------------
# per-variant compiled-program cost reports
# ---------------------------------------------------------------------------

def _example_step_args(cluster):
    """An idle (state, StepInput) pair shaped for ``cluster`` — the
    prewarm shapes, which are exactly what the serving path
    dispatches. The state is converted to ``ShapeDtypeStruct``s so
    lowering never touches live device buffers (safe to run while the
    driver loop keeps dispatching — donation cannot invalidate an
    abstract aval)."""
    import jax
    import jax.numpy as jnp

    from rdma_paxos_tpu.consensus.log import META_W
    from rdma_paxos_tpu.consensus.step import StepInput

    cfg, R, B = cluster.cfg, cluster.R, cluster.cfg.batch_slots
    G = getattr(cluster, "G", None)
    lead = (G, R) if G is not None else (R,)
    state = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), cluster.state)
    inp = StepInput(
        batch_data=jnp.zeros(lead + (B, cfg.slot_words), jnp.int32),
        batch_meta=jnp.zeros(lead + (B, META_W), jnp.int32),
        batch_count=jnp.zeros(lead, jnp.int32),
        timeout_fired=jnp.zeros(lead, jnp.int32),
        peer_mask=jnp.ones(lead + (R,), jnp.int32),
        apply_done=jnp.zeros(lead, jnp.int32),
        queue_depth=jnp.zeros(lead, jnp.int32))
    return state, inp, lead


def _analyze(lowered) -> dict:
    """flops / bytes-accessed / memory sizes of one compiled variant
    (best-effort: backends may omit pieces of the analysis)."""
    out: dict = {}
    try:
        compiled = lowered.compile()
    except Exception as exc:  # noqa: BLE001 — report, don't crash
        return dict(error=repr(exc))
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            out["flops"] = float(ca.get("flops", 0.0))
            out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    except Exception:  # noqa: BLE001
        pass
    try:
        ma = compiled.memory_analysis()
        mem = {}
        for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes", "alias_size_in_bytes",
                     "generated_code_size_in_bytes"):
            v = getattr(ma, attr, None)
            if v is not None:
                mem[attr] = int(v)
        peak = (mem.get("argument_size_in_bytes", 0)
                + mem.get("output_size_in_bytes", 0)
                + mem.get("temp_size_in_bytes", 0))
        mem["peak_bytes"] = peak
        out["memory"] = mem
    except Exception:  # noqa: BLE001
        pass
    return out


def _unpack_build(built):
    """Engines disagree on the builder return shape: SimCluster gives
    the callable, ShardedCluster a ``(callable, cache_key)`` pair."""
    if isinstance(built, tuple):
        return built[0]
    return built


def program_report(cluster, *, tiers: Sequence[int] = ()) -> dict:
    """Cost/memory report for every step variant this cluster serves
    (full + stable step, plus the requested fused-burst tiers) —
    the static complement of the runtime counters: what one dispatch
    COSTS, per STEP_CACHE variant. Lowering reuses the live state's
    shapes; nothing is executed or donated."""
    import jax

    from rdma_paxos_tpu.consensus.log import META_W

    state, inp, lead = _example_step_args(cluster)
    cfg, B = cluster.cfg, cluster.cfg.batch_slots
    variants = []
    for elections in (True, False):
        fn = _unpack_build(cluster._build_step(elections=elections))
        row = dict(variant=("step/full" if elections else "step/stable"))
        row.update(_analyze(fn.lower(state, inp)))
        variants.append(row)
    import jax.numpy as jnp
    for K in tiers:
        fn = _unpack_build(cluster._burst_fn(K))
        row = dict(variant="burst/K=%d" % K)
        row.update(_analyze(fn.lower(
            state,
            jnp.zeros((K,) + lead + (B, cfg.slot_words), jnp.int32),
            jnp.zeros((K,) + lead + (B, META_W), jnp.int32),
            jnp.zeros((K,) + lead, jnp.int32),
            jnp.ones(lead + (cluster.R,), jnp.int32),
            jnp.zeros(lead, jnp.int32),
            jnp.zeros(lead, jnp.int32))))
        variants.append(row)
    return dict(
        schema=1, kind="program_report", anchor=clock_anchor(),
        backend=jax.default_backend(),
        engine=getattr(cluster, "_mode", "sim"),
        n_replicas=cluster.R,
        n_groups=getattr(cluster, "G", 1),
        config=dict(n_slots=cfg.n_slots, slot_bytes=cfg.slot_bytes,
                    window_slots=cfg.window_slots,
                    batch_slots=cfg.batch_slots),
        telemetry=bool(getattr(cluster, "_telemetry", False)),
        audit=bool(getattr(cluster, "_audit", False)),
        variants=variants)


def write_program_report(path: str, cluster, *,
                         tiers: Sequence[int] = ()) -> dict:
    """Atomic ``program_report.json`` artifact next to the bench
    outputs; returns the report dict."""
    rep = program_report(cluster, tiers=tiers)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rep, f, indent=2)
    os.replace(tmp, path)
    rep["path"] = path
    return rep


# ---------------------------------------------------------------------------
# CLI: merge a profiler capture + span dumps into one Perfetto file
# ---------------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rdma_paxos_tpu.obs.device",
        description="Merge a jax.profiler capture dir and span dumps "
                    "into ONE Perfetto timeline on the shared clock "
                    "anchors.")
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="write the merged Perfetto JSON")
    mp.add_argument("--profile-dir", default=None,
                    help="a ProfilerSession log dir (trace.json.gz "
                         "inside)")
    mp.add_argument("--wall-start", type=float, default=None,
                    help="the capture's wall_start (time.time() at "
                         "start_trace) — required with --profile-dir")
    mp.add_argument("--spans", nargs="*", default=[],
                    help="raw span dump JSONs")
    mp.add_argument("-o", "--out", required=True)
    args = ap.parse_args(argv)

    dumps = []
    for p in args.spans:
        with open(p) as f:
            dumps.append(json.load(f))
    dev_events = None
    if args.profile_dir:
        if args.wall_start is None:
            raise SystemExit("--profile-dir requires --wall-start "
                             "(the capture's start wall time)")
        dev_events = load_profiler_dir(args.profile_dir)
    doc = merge_timeline(dumps, device_events=dev_events,
                         device_wall_start=args.wall_start)
    with open(args.out, "w") as f:
        json.dump(doc, f)
    print("wrote %s: %d events (%d device, %d host-phase) — load in "
          "https://ui.perfetto.dev"
          % (args.out, len(doc["traceEvents"]),
             doc["otherData"]["device_events"],
             doc["otherData"]["host_phase_events"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
