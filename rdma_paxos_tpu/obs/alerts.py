"""SLO alerting — a small declarative rule engine over the metrics
registry.

The registry (obs/metrics.py) answers "what is the value"; nothing
before this module answers "should someone be paged". Rules are plain
dicts (JSON-serializable — they ride health snapshots verbatim), each
naming a metric, an evaluation ``kind``, a threshold, a ``severity``
(``page`` | ``warn``) and an optional ``for_evals`` hysteresis (the
condition must hold for N consecutive evaluations before the alert
fires — transient blips don't page). The engine is evaluated from the
driver/daemon host loops on a cadence; it never runs inside jitted
code and never blocks the data path.

Rule kinds:

* ``counter_nonzero`` — fires while the summed counter is > 0 (a
  latched condition: digest divergence never un-happens).
* ``counter_rate`` — fires when the counter's delta since the previous
  evaluation exceeds ``threshold`` (e.g. ``rebase_stalled`` ticking).
* ``gauge_cmp`` — compares a gauge against ``value`` with ``op`` in
  ``< > == != <= >=`` (e.g. ``cluster_leader == -1`` = leaderless).
* ``hist_quantile`` — estimates quantile ``q`` from the fixed-bucket
  histogram (bucket upper bound containing the q-th observation;
  series with the same name are merged — same ladder by design) and
  compares it against ``threshold`` with ``op``.

Two WINDOW-DOMAIN kinds evaluate against the attached
:class:`~rdma_paxos_tpu.obs.series.TimeSeriesStore` (``series=``)
instead of the instantaneous snapshot — without a store they are
silent, the same contract the telemetry-backed rules use when the
device series don't exist:

* ``rate_window`` — the counter's average per-second rate over the
  trailing ``window_s`` (or ``window_steps``) exceeds ``threshold``
  (windows anchor at the series' last sample — step+wall domain of
  the DATA, deterministic, not the realtime clock).
* ``burn_rate`` — multi-window SLO burn rate over a latency
  histogram: the fraction of observations above ``bound`` (a bucket
  boundary) in a window, divided by the error budget
  ``1 - objective``. Fires only when BOTH the fast window
  (``fast_window_s``) and the slow window (``slow_window_s``) burn
  faster than ``burn_threshold`` — the fast window catches the
  regression quickly, the slow window keeps a transient blip from
  paging (the classic multi-window burn-rate pager), and
  ``for_evals`` hysteresis still applies on top.

Metric matching aggregates across label sets by default (counters are
summed, gauges take the configured ``agg`` — max by default);
``labels={...}`` restricts a rule to exact label pairs.

Firing state is exported two ways: ``alert_firing{alert=<name>}``
gauges in the registry (scrapable like any other series) and
:meth:`AlertEngine.state` (embedded in health snapshots). Transitions
emit ``alert_fired`` / ``alert_resolved`` trace events when a trace
ring is attached.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

PAGE = "page"
WARN = "warn"

KINDS = ("counter_nonzero", "counter_rate", "gauge_cmp",
         "hist_quantile", "rate_window", "burn_rate")

_OPS = {
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


def default_rules(*, commit_p99_ceiling_s: float = 0.5,
                  leaderless_evals: int = 5,
                  election_storm_rate: int = 3,
                  log_headroom_floor: int = 16,
                  commit_slo_bound_s: float = 0.25,
                  read_slo_bound_us: float = 5000.0,
                  slo_objective: float = 0.99,
                  burn_fast_s: float = 30.0,
                  burn_slow_s: float = 300.0,
                  burn_threshold: float = 6.0,
                  cdc_lag_ceiling: int = 4096,
                  txn_abort_rate: int = 3) -> List[dict]:
    """The stock SLO rule set: digest mismatch pages immediately (a
    correctness violation, not a performance blip); sustained
    leaderlessness pages; commit-latency p99 above the ceiling and a
    ticking rebase stall warn.

    Two rules read the DEVICE-telemetry series (``telemetry=True``
    clusters — obs/device.py; without telemetry the series don't
    exist, so the rules are silent):

    * ``election_storm`` (``counter_rate``, page) — more than
      ``election_storm_rate`` elections started ON DEVICE between two
      evaluations, sustained for 2 evals: leadership is churning
      faster than timers should ever fire (flapping links, a wedged
      leader host, timeout skew).
    * ``log_headroom_low`` (``gauge_cmp`` with ``agg="min"``, warn) —
      some replica's ring reported fewer than ``log_headroom_floor``
      free slots inside a dispatch: appends are about to stall on
      ring capacity (pruning/apply is falling behind).

    ``repair_failed`` (``counter_nonzero``, page, LATCHED — the
    counter never decrements) fires when the self-healing pipeline
    (``runtime/repair.py``) exhausted its bounded donor retries for a
    quarantined replica and escalated: automated repair gave up, an
    operator must act. Silent on clusters that never escalate (the
    metric does not exist until the first escalation).

    Two ``burn_rate`` rules page on the serving SLOs — the
    window-domain replacement for eyeballing instantaneous p99s
    (which the ``commit_latency_p99`` warn rule still does, for
    continuity): ``commit_latency_slo_burn`` pages when more than
    ``burn_threshold`` times the error budget (``1 - slo_objective``
    of commits slower than ``commit_slo_bound_s``) burns in BOTH the
    fast and slow windows; ``read_latency_slo_burn`` is the same over
    ``read_latency_us`` (the PR 10 read path). Both bounds sit on
    bucket boundaries of their ladders by construction. Silent
    without an attached ``series=`` store (``AlertEngine(series=)``)
    — the drivers always attach one.
    """
    return [
        dict(name="digest_divergence", severity=PAGE,
             kind="counter_nonzero", metric="audit_divergence_total"),
        dict(name="leaderless", severity=PAGE, kind="gauge_cmp",
             metric="cluster_leader", op="==", value=-1,
             for_evals=leaderless_evals),
        dict(name="commit_latency_p99", severity=WARN,
             kind="hist_quantile", metric="commit_latency_seconds",
             q=0.99, op=">", threshold=commit_p99_ceiling_s,
             for_evals=2),
        dict(name="rebase_stalled", severity=WARN, kind="counter_rate",
             metric="rebase_stalled", threshold=0),
        dict(name="election_storm", severity=PAGE, kind="counter_rate",
             metric="device_elections_started_total",
             threshold=election_storm_rate, for_evals=2),
        dict(name="log_headroom_low", severity=WARN, kind="gauge_cmp",
             metric="device_log_headroom", op="<",
             value=log_headroom_floor, agg="min"),
        dict(name="repair_failed", severity=PAGE,
             kind="counter_nonzero", metric="repair_escalated_total"),
        dict(name="commit_latency_slo_burn", severity=PAGE,
             kind="burn_rate", metric="commit_latency_seconds",
             bound=commit_slo_bound_s, objective=slo_objective,
             fast_window_s=burn_fast_s, slow_window_s=burn_slow_s,
             burn_threshold=burn_threshold, for_evals=2),
        dict(name="read_latency_slo_burn", severity=PAGE,
             kind="burn_rate", metric="read_latency_us",
             bound=read_slo_bound_us, objective=slo_objective,
             fast_window_s=burn_fast_s, slow_window_s=burn_slow_s,
             burn_threshold=burn_threshold, for_evals=2),
        # streams backpressure (PR 16): the CDC/watch pump is falling
        # behind the committed frontier on some group — consumers are
        # about to hit overflow-and-resume. Sustained (2 evals): a
        # one-step burst backlog is normal. Silent without a streams
        # hub (the gauge does not exist until one is attached).
        dict(name="cdc_backpressure", severity=WARN, kind="gauge_cmp",
             metric="cdc_lag_entries", op=">", value=cdc_lag_ceiling,
             agg="max", for_evals=2),
        # more than txn_abort_rate transaction aborts (any reason —
        # conflict, timeout, failover) between two evaluations,
        # sustained: the commit lane is thrashing (hot-key contention
        # or leadership churn eating the 2PC window). Silent on
        # clusters without a coordinator (counter never exists).
        dict(name="txn_abort_rate", severity=WARN, kind="counter_rate",
             metric="txn_aborted_total", threshold=txn_abort_rate,
             for_evals=2),
    ]


def _split_key(key: str) -> Tuple[str, Dict[str, str]]:
    from rdma_paxos_tpu.obs.metrics import parse_key
    base, pairs = parse_key(key)
    return base, dict(pairs)


def _match(section: dict, metric: str,
           labels: Optional[dict]) -> List:
    out = []
    for key, val in section.items():
        base, pairs = _split_key(key)
        if base != metric:
            continue
        if labels and any(pairs.get(k) != str(v)
                          for k, v in labels.items()):
            continue
        out.append(val)
    return out


def _quantile(hists: Sequence[dict], q: float) -> Optional[float]:
    """Upper bound of the bucket containing the q-th observation across
    merged fixed-bucket histograms (same ladder by design)."""
    total = sum(h["count"] for h in hists)
    if total == 0:
        return None
    merged: Dict[str, int] = {}
    for h in hists:
        for bound, c in h["buckets"].items():
            merged[bound] = merged.get(bound, 0) + c
    finite = sorted(((float(b), c) for b, c in merged.items()
                     if b != "+Inf"))
    need = q * total
    cum = 0
    for bound, c in finite:
        cum += c
        if cum >= need:
            return bound
    return float("inf")


def _validate_rule(r: dict, seen_names) -> None:
    """Reject an incomplete/unknown rule at registration time — the
    one place a bad rule may raise (see the engine constructor)."""
    if "name" not in r or "metric" not in r:
        raise ValueError(f"rule missing name/metric: {r}")
    if r.get("kind") not in KINDS:
        raise ValueError(
            f"rule {r['name']!r}: unknown kind {r.get('kind')!r}"
            f" (known: {KINDS})")
    if r["name"] in seen_names:
        raise ValueError(f"duplicate rule name {r['name']!r}")
    kind = r["kind"]
    if kind == "gauge_cmp":
        if r.get("op") not in _OPS or "value" not in r:
            raise ValueError(
                f"rule {r['name']!r}: gauge_cmp needs op in "
                f"{sorted(_OPS)} and a value")
    elif kind == "hist_quantile":
        if "threshold" not in r:
            raise ValueError(
                f"rule {r['name']!r}: hist_quantile needs a "
                "threshold")
        if r.get("op", ">") not in _OPS:
            raise ValueError(
                f"rule {r['name']!r}: bad op {r.get('op')!r}")
    elif kind == "rate_window":
        if "threshold" not in r:
            raise ValueError(
                f"rule {r['name']!r}: rate_window needs a "
                "threshold")
        if not (r.get("window_s") or r.get("window_steps")):
            raise ValueError(
                f"rule {r['name']!r}: rate_window needs "
                "window_s or window_steps")
        if r.get("op", ">") not in _OPS:
            raise ValueError(
                f"rule {r['name']!r}: bad op {r.get('op')!r}")
    elif kind == "burn_rate":
        for field in ("bound", "objective", "fast_window_s",
                      "slow_window_s"):
            if field not in r:
                raise ValueError(
                    f"rule {r['name']!r}: burn_rate needs "
                    f"{field}")
        if not 0.0 < float(r["objective"]) < 1.0:
            raise ValueError(
                f"rule {r['name']!r}: objective must be in "
                "(0, 1)")
        if float(r["slow_window_s"]) <= float(
                r["fast_window_s"]):
            raise ValueError(
                f"rule {r['name']!r}: slow_window_s must "
                "exceed fast_window_s")


class AlertEngine:
    """Evaluates a declarative rule list against registry snapshots,
    with per-rule hysteresis and firing-state export."""

    def __init__(self, registry, rules: Optional[Sequence[dict]] = None,
                 *, trace=None, series=None):
        self.registry = registry
        self.trace = trace
        # the TimeSeriesStore the window-domain kinds (rate_window /
        # burn_rate) evaluate against; without one those rules are
        # silent — never an error (same contract as telemetry rules
        # on telemetry-off clusters)
        self.series = series
        self.rules = [dict(r) for r in (rules if rules is not None
                                        else default_rules())]
        seen = set()
        for r in self.rules:
            # kind-specific completeness is checked HERE, not at
            # evaluation time: the engine runs inside the driver poll
            # loop, where a KeyError would be a fatal step crash that
            # fails every inflight commit — construction (and
            # add_rule, the same gate) is the only place a bad rule
            # may raise
            _validate_rule(r, seen)
            seen.add(r["name"])
        self._lock = threading.Lock()
        # alert→action hooks: fn(name, severity) called on each FIRE
        # transition (outside the engine lock; exceptions are swallowed
        # — an acting hook must never kill the evaluating poll loop).
        # The repair pipeline registers here so a digest-divergence
        # page triggers quarantine immediately.
        self._hooks: List = []
        self._st: Dict[str, dict] = {
            r["name"]: dict(severity=r.get("severity", WARN),
                            firing=False, pending=0, value=None,
                            since_eval=None, since=None,
                            duration_s=None, fired_count=0)
            for r in self.rules}
        self._prev_counter: Dict[str, float] = {}
        self.evals = 0

    # ---------------- evaluation ----------------

    def _eval_rule(self, rule: dict, snap: dict):
        kind = rule["kind"]
        metric, labels = rule["metric"], rule.get("labels")
        if kind == "counter_nonzero":
            total = sum(_match(snap["counters"], metric, labels))
            return total, total > 0
        if kind == "counter_rate":
            total = sum(_match(snap["counters"], metric, labels))
            prev = self._prev_counter.get(rule["name"])
            self._prev_counter[rule["name"]] = total
            if prev is None:
                return 0, False      # first sighting: establish baseline
            delta = total - prev
            return delta, delta > rule.get("threshold", 0)
        if kind == "gauge_cmp":
            vals = _match(snap["gauges"], metric, labels)
            if not vals:
                return None, False
            agg = rule.get("agg", "max")
            value = (min(vals) if agg == "min" else
                     max(vals) if agg == "max" else vals[0])
            return value, _OPS[rule["op"]](value, rule["value"])
        if kind == "hist_quantile":
            hists = _match(snap["histograms"], metric, labels)
            value = _quantile(hists, rule.get("q", 0.99)) \
                if hists else None
            if value is None:
                return None, False
            return value, _OPS[rule.get("op", ">")](value,
                                                    rule["threshold"])
        if kind == "rate_window":
            rate = self._window_rate(rule)
            if rate is None:
                return None, False
            return rate, _OPS[rule.get("op", ">")](rate,
                                                   rule["threshold"])
        if kind == "burn_rate":
            fast = self._burn(rule, float(rule["fast_window_s"]))
            slow = self._burn(rule, float(rule["slow_window_s"]))
            if fast is None or slow is None:
                return fast, False
            thresh = float(rule.get("burn_threshold", 1.0))
            return fast, fast > thresh and slow > thresh
        raise AssertionError(kind)

    # ---------------- window-domain evaluation (series store) ----------

    def _window_rate(self, rule: dict) -> Optional[float]:
        """Summed per-second rate of every matching counter series
        over the rule's trailing window; None until the store holds
        enough history."""
        if self.series is None:
            return None
        kw = (dict(wall_s=float(rule["window_s"]))
              if rule.get("window_s")
              else dict(steps=int(rule["window_steps"])))
        total, found = 0.0, False
        for key in self.series.match(rule["metric"],
                                     rule.get("labels")):
            r = self.series.window_rate(key, **kw)
            if r is not None:
                total += r
                found = True
        return total if found else None

    def _burn(self, rule: dict, window_s: float) -> Optional[float]:
        """SLO burn rate over one window: the fraction of histogram
        observations ABOVE ``bound`` across all matching label sets,
        divided by the error budget ``1 - objective``. The bound must
        sit on a bucket boundary; when it doesn't exactly (float
        drift), the largest retained bound <= it is used — which can
        only OVERcount the bad fraction (conservative paging)."""
        if self.series is None:
            return None
        metric, labels = rule["metric"], rule.get("labels")
        total = good = 0.0
        saw_total = saw_good = False
        for key in self.series.match(metric, labels, sub="count"):
            d = self.series.window_delta(key, wall_s=window_s)
            if d is not None:
                total += d
                saw_total = True
                # the parent key ("name{labels}") indexes the le
                # ladder this histogram retained; repr(float) is
                # stable through the store's float round-trip, so
                # rebuilding the sub-key from the parsed bound hits
                # the exact retained series
                parent = key.rsplit("|", 1)[0]
                bounds = [b for b in self.series.le_bounds(parent)
                          if b <= float(rule["bound"]) + 1e-12]
                if bounds:
                    g = self.series.window_delta(
                        f"{parent}|le|{bounds[-1]!r}",
                        wall_s=window_s)
                    if g is not None:
                        good += g
                        saw_good = True
        if not saw_total or total <= 0.0:
            return None
        bad_frac = max(0.0, (total - (good if saw_good else 0.0))
                       / total)
        return bad_frac / max(1e-12, 1.0 - float(rule["objective"]))

    @staticmethod
    def _exemplars(snap: dict, rule: dict, limit: int = 8) -> List[str]:
        """Exemplar trace ids for a firing rule, harvested from its
        metric's histogram reservoirs — slowest buckets first, because
        the tail is what the page is ABOUT. Empty when the metric has
        no histogram (counter/gauge rules) or no exemplars recorded."""
        def _bound(label: str) -> float:
            return float("inf") if label == "+Inf" else float(label)

        ids: List[str] = []
        for h in _match(snap.get("histograms", {}), rule["metric"],
                        rule.get("labels")):
            ex = h.get("exemplars")
            if not ex:
                continue
            for label in sorted(ex, key=_bound, reverse=True):
                for tid, _v in ex[label]:
                    if tid not in ids:
                        ids.append(tid)
        return ids[:limit]

    def evaluate(self,
                 snap: Optional[dict] = None) -> Dict[str, List[str]]:
        """One evaluation pass; returns the transitions
        ``{"fired": [...], "resolved": [...]}``. Firing gauges
        (``alert_firing{alert=name}``) are refreshed every pass.
        ``snap`` lets the caller share one registry snapshot with the
        series-store sampling it just did (the drivers' cadence)."""
        if snap is None:
            snap = self.registry.snapshot()
        fired: List[str] = []
        resolved: List[str] = []
        with self._lock:
            self.evals += 1
            for rule in self.rules:
                value, cond = self._eval_rule(rule, snap)
                st = self._st[rule["name"]]
                st["value"] = value
                if cond:
                    st["pending"] += 1
                    if (not st["firing"]
                            and st["pending"]
                            >= int(rule.get("for_evals", 1))):
                        st["firing"] = True
                        st["since_eval"] = self.evals
                        st["since"] = time.time()
                        st["fired_count"] += 1
                        ex = self._exemplars(snap, rule)
                        if ex:
                            # the firing carries concrete evidence:
                            # trace ids from the metric's histogram
                            # reservoir, slowest buckets first —
                            # resolvable in the postmortem bundle's
                            # span dump / merged Perfetto timeline
                            st["exemplars"] = ex
                        fired.append(rule["name"])
                else:
                    st["pending"] = 0
                    if st["firing"]:
                        st["firing"] = False
                        st["since_eval"] = None
                        st["since"] = None
                        resolved.append(rule["name"])
                self.registry.set("alert_firing",
                                  1 if st["firing"] else 0,
                                  alert=rule["name"])
        if self.trace is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            for n in fired:
                kw = dict(alert=n,
                          severity=self._st[n]["severity"],
                          value=self._st[n]["value"])
                if self._st[n].get("exemplars"):
                    kw["exemplars"] = self._st[n]["exemplars"]
                self.trace.record(_trace.ALERT_FIRED, **kw)
            for n in resolved:
                self.trace.record(_trace.ALERT_RESOLVED, alert=n)
        for n in fired:
            for hook in self._hooks:
                try:
                    hook(n, self._st[n]["severity"])
                except Exception:  # noqa: BLE001 — hooks never kill
                    pass           # the evaluating poll loop
        return dict(fired=fired, resolved=resolved)

    def add_hook(self, fn) -> None:
        """Register an alert→action hook ``fn(name, severity)`` —
        invoked on every fire transition, after state/trace export."""
        self._hooks.append(fn)

    def add_rule(self, rule: dict) -> None:
        """Register one more rule after construction — the attach path
        for subsystems that ship their own stock rules (topology skew).
        Same validation gate as the constructor; duplicate names are
        rejected so a double attach can't shadow state."""
        r = dict(rule)
        _validate_rule(r, {x["name"] for x in self.rules})
        with self._lock:
            self.rules.append(r)
            self._st[r["name"]] = dict(
                severity=r.get("severity", WARN), firing=False,
                pending=0, value=None, since_eval=None, since=None,
                duration_s=None, fired_count=0)

    # ---------------- state export ----------------

    def severity(self, name: str) -> str:
        return self._st[name]["severity"]

    def firing(self, severity: Optional[str] = None) -> List[str]:
        with self._lock:
            return [n for n, st in self._st.items()
                    if st["firing"]
                    and (severity is None or st["severity"] == severity)]

    def state(self) -> dict:
        """Per-rule firing state for health snapshots (plain data).
        Firing rules carry ``since`` (wall time the fire transition
        happened) and a live ``duration_s`` — the age the console
        renders next to each firing alert."""
        now = time.time()
        with self._lock:
            out = {}
            for n, st in self._st.items():
                d = dict(st)
                d["duration_s"] = (round(now - d["since"], 3)
                                   if d["firing"] and d["since"]
                                   is not None else None)
                out[n] = d
            return out
