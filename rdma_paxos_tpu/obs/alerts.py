"""SLO alerting — a small declarative rule engine over the metrics
registry.

The registry (obs/metrics.py) answers "what is the value"; nothing
before this module answers "should someone be paged". Rules are plain
dicts (JSON-serializable — they ride health snapshots verbatim), each
naming a metric, an evaluation ``kind``, a threshold, a ``severity``
(``page`` | ``warn``) and an optional ``for_evals`` hysteresis (the
condition must hold for N consecutive evaluations before the alert
fires — transient blips don't page). The engine is evaluated from the
driver/daemon host loops on a cadence; it never runs inside jitted
code and never blocks the data path.

Rule kinds:

* ``counter_nonzero`` — fires while the summed counter is > 0 (a
  latched condition: digest divergence never un-happens).
* ``counter_rate`` — fires when the counter's delta since the previous
  evaluation exceeds ``threshold`` (e.g. ``rebase_stalled`` ticking).
* ``gauge_cmp`` — compares a gauge against ``value`` with ``op`` in
  ``< > == != <= >=`` (e.g. ``cluster_leader == -1`` = leaderless).
* ``hist_quantile`` — estimates quantile ``q`` from the fixed-bucket
  histogram (bucket upper bound containing the q-th observation;
  series with the same name are merged — same ladder by design) and
  compares it against ``threshold`` with ``op``.

Metric matching aggregates across label sets by default (counters are
summed, gauges take the configured ``agg`` — max by default);
``labels={...}`` restricts a rule to exact label pairs.

Firing state is exported two ways: ``alert_firing{alert=<name>}``
gauges in the registry (scrapable like any other series) and
:meth:`AlertEngine.state` (embedded in health snapshots). Transitions
emit ``alert_fired`` / ``alert_resolved`` trace events when a trace
ring is attached.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

PAGE = "page"
WARN = "warn"

KINDS = ("counter_nonzero", "counter_rate", "gauge_cmp",
         "hist_quantile")

_OPS = {
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


def default_rules(*, commit_p99_ceiling_s: float = 0.5,
                  leaderless_evals: int = 5,
                  election_storm_rate: int = 3,
                  log_headroom_floor: int = 16) -> List[dict]:
    """The stock SLO rule set: digest mismatch pages immediately (a
    correctness violation, not a performance blip); sustained
    leaderlessness pages; commit-latency p99 above the ceiling and a
    ticking rebase stall warn.

    Two rules read the DEVICE-telemetry series (``telemetry=True``
    clusters — obs/device.py; without telemetry the series don't
    exist, so the rules are silent):

    * ``election_storm`` (``counter_rate``, page) — more than
      ``election_storm_rate`` elections started ON DEVICE between two
      evaluations, sustained for 2 evals: leadership is churning
      faster than timers should ever fire (flapping links, a wedged
      leader host, timeout skew).
    * ``log_headroom_low`` (``gauge_cmp`` with ``agg="min"``, warn) —
      some replica's ring reported fewer than ``log_headroom_floor``
      free slots inside a dispatch: appends are about to stall on
      ring capacity (pruning/apply is falling behind).

    ``repair_failed`` (``counter_nonzero``, page, LATCHED — the
    counter never decrements) fires when the self-healing pipeline
    (``runtime/repair.py``) exhausted its bounded donor retries for a
    quarantined replica and escalated: automated repair gave up, an
    operator must act. Silent on clusters that never escalate (the
    metric does not exist until the first escalation).
    """
    return [
        dict(name="digest_divergence", severity=PAGE,
             kind="counter_nonzero", metric="audit_divergence_total"),
        dict(name="leaderless", severity=PAGE, kind="gauge_cmp",
             metric="cluster_leader", op="==", value=-1,
             for_evals=leaderless_evals),
        dict(name="commit_latency_p99", severity=WARN,
             kind="hist_quantile", metric="commit_latency_seconds",
             q=0.99, op=">", threshold=commit_p99_ceiling_s,
             for_evals=2),
        dict(name="rebase_stalled", severity=WARN, kind="counter_rate",
             metric="rebase_stalled", threshold=0),
        dict(name="election_storm", severity=PAGE, kind="counter_rate",
             metric="device_elections_started_total",
             threshold=election_storm_rate, for_evals=2),
        dict(name="log_headroom_low", severity=WARN, kind="gauge_cmp",
             metric="device_log_headroom", op="<",
             value=log_headroom_floor, agg="min"),
        dict(name="repair_failed", severity=PAGE,
             kind="counter_nonzero", metric="repair_escalated_total"),
    ]


def _split_key(key: str) -> Tuple[str, Dict[str, str]]:
    base, sep, rest = key.partition("{")
    if not sep:
        return base, {}
    pairs = {}
    for part in rest.rstrip("}").split(","):
        if part:
            k, _, v = part.partition("=")
            pairs[k] = v
    return base, pairs


def _match(section: dict, metric: str,
           labels: Optional[dict]) -> List:
    out = []
    for key, val in section.items():
        base, pairs = _split_key(key)
        if base != metric:
            continue
        if labels and any(pairs.get(k) != str(v)
                          for k, v in labels.items()):
            continue
        out.append(val)
    return out


def _quantile(hists: Sequence[dict], q: float) -> Optional[float]:
    """Upper bound of the bucket containing the q-th observation across
    merged fixed-bucket histograms (same ladder by design)."""
    total = sum(h["count"] for h in hists)
    if total == 0:
        return None
    merged: Dict[str, int] = {}
    for h in hists:
        for bound, c in h["buckets"].items():
            merged[bound] = merged.get(bound, 0) + c
    finite = sorted(((float(b), c) for b, c in merged.items()
                     if b != "+Inf"))
    need = q * total
    cum = 0
    for bound, c in finite:
        cum += c
        if cum >= need:
            return bound
    return float("inf")


class AlertEngine:
    """Evaluates a declarative rule list against registry snapshots,
    with per-rule hysteresis and firing-state export."""

    def __init__(self, registry, rules: Optional[Sequence[dict]] = None,
                 *, trace=None):
        self.registry = registry
        self.trace = trace
        self.rules = [dict(r) for r in (rules if rules is not None
                                        else default_rules())]
        seen = set()
        for r in self.rules:
            if "name" not in r or "metric" not in r:
                raise ValueError(f"rule missing name/metric: {r}")
            if r.get("kind") not in KINDS:
                raise ValueError(
                    f"rule {r['name']!r}: unknown kind {r.get('kind')!r}"
                    f" (known: {KINDS})")
            if r["name"] in seen:
                raise ValueError(f"duplicate rule name {r['name']!r}")
            seen.add(r["name"])
            # kind-specific completeness is checked HERE, not at
            # evaluation time: the engine runs inside the driver poll
            # loop, where a KeyError would be a fatal step crash that
            # fails every inflight commit — construction is the only
            # place a bad rule may raise
            kind = r["kind"]
            if kind == "gauge_cmp":
                if r.get("op") not in _OPS or "value" not in r:
                    raise ValueError(
                        f"rule {r['name']!r}: gauge_cmp needs op in "
                        f"{sorted(_OPS)} and a value")
            elif kind == "hist_quantile":
                if "threshold" not in r:
                    raise ValueError(
                        f"rule {r['name']!r}: hist_quantile needs a "
                        "threshold")
                if r.get("op", ">") not in _OPS:
                    raise ValueError(
                        f"rule {r['name']!r}: bad op {r.get('op')!r}")
        self._lock = threading.Lock()
        # alert→action hooks: fn(name, severity) called on each FIRE
        # transition (outside the engine lock; exceptions are swallowed
        # — an acting hook must never kill the evaluating poll loop).
        # The repair pipeline registers here so a digest-divergence
        # page triggers quarantine immediately.
        self._hooks: List = []
        self._st: Dict[str, dict] = {
            r["name"]: dict(severity=r.get("severity", WARN),
                            firing=False, pending=0, value=None,
                            since_eval=None, fired_count=0)
            for r in self.rules}
        self._prev_counter: Dict[str, float] = {}
        self.evals = 0

    # ---------------- evaluation ----------------

    def _eval_rule(self, rule: dict, snap: dict):
        kind = rule["kind"]
        metric, labels = rule["metric"], rule.get("labels")
        if kind == "counter_nonzero":
            total = sum(_match(snap["counters"], metric, labels))
            return total, total > 0
        if kind == "counter_rate":
            total = sum(_match(snap["counters"], metric, labels))
            prev = self._prev_counter.get(rule["name"])
            self._prev_counter[rule["name"]] = total
            if prev is None:
                return 0, False      # first sighting: establish baseline
            delta = total - prev
            return delta, delta > rule.get("threshold", 0)
        if kind == "gauge_cmp":
            vals = _match(snap["gauges"], metric, labels)
            if not vals:
                return None, False
            agg = rule.get("agg", "max")
            value = (min(vals) if agg == "min" else
                     max(vals) if agg == "max" else vals[0])
            return value, _OPS[rule["op"]](value, rule["value"])
        if kind == "hist_quantile":
            hists = _match(snap["histograms"], metric, labels)
            value = _quantile(hists, rule.get("q", 0.99)) \
                if hists else None
            if value is None:
                return None, False
            return value, _OPS[rule.get("op", ">")](value,
                                                    rule["threshold"])
        raise AssertionError(kind)

    def evaluate(self) -> Dict[str, List[str]]:
        """One evaluation pass; returns the transitions
        ``{"fired": [...], "resolved": [...]}``. Firing gauges
        (``alert_firing{alert=name}``) are refreshed every pass."""
        snap = self.registry.snapshot()
        fired: List[str] = []
        resolved: List[str] = []
        with self._lock:
            self.evals += 1
            for rule in self.rules:
                value, cond = self._eval_rule(rule, snap)
                st = self._st[rule["name"]]
                st["value"] = value
                if cond:
                    st["pending"] += 1
                    if (not st["firing"]
                            and st["pending"]
                            >= int(rule.get("for_evals", 1))):
                        st["firing"] = True
                        st["since_eval"] = self.evals
                        st["fired_count"] += 1
                        fired.append(rule["name"])
                else:
                    st["pending"] = 0
                    if st["firing"]:
                        st["firing"] = False
                        st["since_eval"] = None
                        resolved.append(rule["name"])
                self.registry.set("alert_firing",
                                  1 if st["firing"] else 0,
                                  alert=rule["name"])
        if self.trace is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            for n in fired:
                self.trace.record(_trace.ALERT_FIRED, alert=n,
                                  severity=self._st[n]["severity"],
                                  value=self._st[n]["value"])
            for n in resolved:
                self.trace.record(_trace.ALERT_RESOLVED, alert=n)
        for n in fired:
            for hook in self._hooks:
                try:
                    hook(n, self._st[n]["severity"])
                except Exception:  # noqa: BLE001 — hooks never kill
                    pass           # the evaluating poll loop
        return dict(fired=fired, resolved=resolved)

    def add_hook(self, fn) -> None:
        """Register an alert→action hook ``fn(name, severity)`` —
        invoked on every fire transition, after state/trace export."""
        self._hooks.append(fn)

    # ---------------- state export ----------------

    def severity(self, name: str) -> str:
        return self._st[name]["severity"]

    def firing(self, severity: Optional[str] = None) -> List[str]:
        with self._lock:
            return [n for n, st in self._st.items()
                    if st["firing"]
                    and (severity is None or st["severity"] == severity)]

    def state(self) -> dict:
        """Per-rule firing state for health snapshots (plain data)."""
        with self._lock:
            return {n: dict(st) for n, st in self._st.items()}
