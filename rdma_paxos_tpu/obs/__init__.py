"""Cluster observability subsystem: metrics registry, protocol trace
ring, and health snapshots.

Three parts, all host-side, all zero-dependency (stdlib only):

* :mod:`~rdma_paxos_tpu.obs.metrics` — thread-safe counters, gauges,
  and fixed-bucket histograms with per-replica labels; ``snapshot()``
  and JSON export for the bench harness.
* :mod:`~rdma_paxos_tpu.obs.trace` — a bounded in-memory ring of typed
  protocol events (elections, batches, commit advance, rebase
  applied/stalled, snapshots, membership, proxy enqueue/ack-release),
  dumpable on failure or on demand.
* :mod:`~rdma_paxos_tpu.obs.health` — periodic per-replica health
  snapshot files (role, term, indices, log headroom vs the i32 rebase
  ceiling, inflight waiters, store progress), aggregated live by
  ``ClusterDriver.health()``.
* :mod:`~rdma_paxos_tpu.obs.spans` — causal command tracing: sampled
  end-to-end spans (submit → append ``(term, index)`` → quorum →
  commit → apply → ack) with cross-replica correlation, a step-phase
  profiler, and a Perfetto-loadable Chrome trace exporter.
* :mod:`~rdma_paxos_tpu.obs.clock` — the shared ``(monotonic, wall)``
  anchor pair every dump is stamped with, so trace/health/span
  exports from different processes align on one timebase.
* :mod:`~rdma_paxos_tpu.obs.audit` — silent-divergence auditing: the
  cluster audit ledger over the on-device digest chain (``audit=True``
  compiled steps), the flight recorder, audit artifacts, and the
  first-divergence merge CLI.
* :mod:`~rdma_paxos_tpu.obs.alerts` — declarative SLO alert rules
  (digest mismatch = page, leaderless, commit-latency p99, rebase
  stalls, election storms, low log headroom) evaluated by the
  driver/daemon host loops.
* :mod:`~rdma_paxos_tpu.obs.device` — device telemetry: the host
  consumer of the on-device protocol-counter vector (``telemetry=True``
  compiled steps), the bounded ``jax.profiler`` capture manager, the
  merged span/host-phase/device Perfetto timeline, and per-variant
  compiled-program cost reports.
* :mod:`~rdma_paxos_tpu.obs.series` — time-series retention: the
  registry sampled on the alert cadence into bounded per-series rings
  (counters→windowed rates, gauges→last, histograms→quantile/CDF
  points), persisted as append-only JSONL (cross-host merge = file
  concat) — the substrate of the window-domain SLO rules.
* :mod:`~rdma_paxos_tpu.obs.export` — metrics exposition: the
  Prometheus text renderer and the opt-in localhost HTTP exporter
  (``/metrics`` ``/healthz`` ``/series`` ``/alerts``) the drivers and
  NodeDaemon attach.
* :mod:`~rdma_paxos_tpu.obs.console` — the operator CLI: a live fleet
  table merged from N hosts' health files / scraped endpoints, and
  one-command sha256-manifested postmortem bundles
  (``python -m rdma_paxos_tpu.obs.console``).

HARD RULE: no metrics/trace call may execute inside a
jitted/``shard_map``ped function — instrumentation lives in the host
control plane only, so compiled-step programs (and their cache keys)
are bit-identical with observability on or off. ``tests/test_obs.py``
verifies exactly that.
"""

from __future__ import annotations

from typing import Optional

from rdma_paxos_tpu.obs import (
    alerts, audit, clock, device, export, health, metrics, series,
    spans, trace, tracectx)
from rdma_paxos_tpu.obs.alerts import AlertEngine
from rdma_paxos_tpu.obs.audit import AuditLedger, FlightRecorder
from rdma_paxos_tpu.obs.device import ProfilerSession
from rdma_paxos_tpu.obs.export import OpsExporter
from rdma_paxos_tpu.obs.health import HealthReporter
from rdma_paxos_tpu.obs.metrics import MetricsRegistry
from rdma_paxos_tpu.obs.series import TimeSeriesStore
from rdma_paxos_tpu.obs.spans import SpanRecorder, StepPhaseProfiler
from rdma_paxos_tpu.obs.trace import TraceRing
from rdma_paxos_tpu.obs.tracectx import TraceContext


class Observability:
    """Facade bundling one registry + one trace ring + one span
    recorder — the unit the drivers thread through every layer. Each
    :class:`ClusterDriver` gets its own (isolated, test-friendly);
    module-level code with no driver in scope records against
    :func:`default`."""

    def __init__(self, metrics_registry: Optional[MetricsRegistry] = None,
                 trace_ring: Optional[TraceRing] = None,
                 span_recorder: Optional[SpanRecorder] = None,
                 trace_context: Optional[TraceContext] = None):
        self.metrics = (metrics_registry if metrics_registry is not None
                        else MetricsRegistry())
        self.trace = (trace_ring if trace_ring is not None
                      else TraceRing())
        self.spans = (span_recorder if span_recorder is not None
                      else SpanRecorder())
        self.tracectx = (trace_context if trace_context is not None
                         else TraceContext())

    def snapshot(self) -> dict:
        """Combined point-in-time export: the metrics snapshot plus the
        trace ring's retained events plus the span dump — every part
        stamped with the shared clock anchor. Subsystem traces ride as
        ``traces`` only when some exist, so trace-free snapshots keep
        the pre-trace-plane schema byte-for-byte."""
        out = {"anchor": clock.anchor(),
               "metrics": self.metrics.snapshot(),
               "trace": self.trace.dump(),
               "spans": self.spans.dump()}
        traces = self.tracectx.dump()
        if traces["traces"]:
            out["traces"] = traces
        return out

    def reset(self) -> None:
        self.metrics.reset()
        self.trace.clear()
        self.spans.reset()
        self.tracectx.reset()


_default: Optional[Observability] = None


def default() -> Observability:
    """The process-global facade over the module-level default registry
    and ring (shared with all module-level instrumentation)."""
    global _default
    if _default is None:
        _default = Observability(metrics.default_registry(),
                                 trace.default_ring())
    return _default


__all__ = ["Observability", "MetricsRegistry", "TraceRing",
           "HealthReporter", "SpanRecorder", "StepPhaseProfiler",
           "AuditLedger", "FlightRecorder", "AlertEngine",
           "ProfilerSession", "TimeSeriesStore", "OpsExporter",
           "TraceContext", "default", "metrics", "trace", "health",
           "spans", "clock", "audit", "alerts", "device", "series",
           "export", "tracectx"]
