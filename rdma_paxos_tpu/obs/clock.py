"""Shared obs timebase — one ``(monotonic, wall)`` anchor per process.

The obs surfaces historically mixed clocks: the trace ring records
``time.monotonic()`` (ordering-safe, never steps backwards) while
health snapshots record ``time.time()`` (operator-meaningful, but
steppable). Cross-replica exports — merging span dumps from several
host processes into one Perfetto timeline — need BOTH: monotonic for
intra-process ordering and wall for inter-process alignment.

This module pins the bridge: the anchor pair is captured ONCE per
process (first use), and every dump (trace ring, health snapshot, span
dump, bench report) stamps it verbatim. A reader aligns any monotonic
timestamp ``ts`` from a dump onto the shared wall timebase as::

    wall = anchor["wall"] + (ts - anchor["monotonic"])

which is exact within the process and accurate across processes to
host clock sync (the same budget any distributed tracing system has).

Stdlib only — importable from any layer without JAX.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

_ANCHOR: Optional[Dict[str, float]] = None


def anchor() -> Dict[str, float]:
    """The process's ``{"monotonic": m, "wall": w}`` anchor pair,
    captured back-to-back once on first use and returned (as a copy)
    forever after — every dump from this process carries the SAME
    pair, so all of them align onto one timebase."""
    global _ANCHOR
    if _ANCHOR is None:
        _ANCHOR = {"monotonic": time.monotonic(), "wall": time.time()}
    return dict(_ANCHOR)


def to_wall(ts_monotonic: float,
            anchor_pair: Optional[Dict[str, float]] = None) -> float:
    """Project a monotonic timestamp onto the wall timebase using
    ``anchor_pair`` (a dump's stamped anchor; defaults to this
    process's own)."""
    a = anchor_pair if anchor_pair is not None else anchor()
    return a["wall"] + (ts_monotonic - a["monotonic"])
