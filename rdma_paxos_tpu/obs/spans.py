"""Causal command tracing — cross-replica spans, step-phase
attribution, Perfetto export.

The metrics registry answers "how is the cluster doing"; the trace
ring answers "what did the protocol do"; nothing before this module
answers the question production operation of a replicated serving
stack actually asks: *where did this one slow request spend its time?*

Three parts, all host-side, stdlib-only at import (JAX is touched only
inside the optional fencing path):

* :class:`SpanRecorder` — follows each client command end-to-end:
  session submit → proxy enqueue → leader append (stamped with
  ``(term, index)``) → quorum ack → per-replica commit advance →
  per-replica apply → client ack. Cross-replica correlation is keyed
  by ``(term, index)``: the pair is unique cluster-wide (terms are
  unique per leader by quorum election; indices are the global
  monotone, rebase-corrected log positions), so span dumps from
  different host processes merge into one causal timeline. Sampling
  is rate-limited by default (one command in
  :data:`DEFAULT_SAMPLE_EVERY`) so the hot path stays cheap — an
  unsampled command costs one counter increment; marks on unsampled
  keys are dictionary misses.

* :class:`StepPhaseProfiler` — attributes driver/daemon hot-loop wall
  time to phases (host encode, device dispatch, device sync, quorum
  wait, apply, ack release) and feeds the existing histogram registry
  (``step_phase_us{phase=...}``). Device sync is measured via explicit
  ``jax.block_until_ready`` fencing — OFF by default, because without
  a fence the dispatch phase deliberately conflates enqueue with
  device time (the async-dispatch norm) and fencing serializes the
  pipeline; with ``fence=True`` the sync cost lands in its own
  ``device_sync`` series. Fencing changes no compiled programs
  (``tests/test_spans.py`` guards compiled-step cache keys).

* Chrome trace-event export — :func:`to_chrome_trace` merges one or
  more span dumps (aligned on the shared
  :mod:`~rdma_paxos_tpu.obs.clock` anchor) into a Perfetto-loadable
  JSON object: one track per replica (phase marks) plus one
  critical-path track per sampled command (submit→append→quorum→
  apply→ack segments). ``python -m rdma_paxos_tpu.obs.spans`` merges
  multi-replica span files and prints the critical-path breakdown.

HARD RULE (inherited from the rest of ``obs``): nothing here may run
inside jitted/``shard_map``ped code — all call sites live in the host
control plane, and compiled-step cache keys are bit-identical with
tracing on or off.
"""

from __future__ import annotations

import argparse
import collections
import heapq
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from rdma_paxos_tpu.obs.clock import anchor as clock_anchor
from rdma_paxos_tpu.obs.metrics import LATENCY_BUCKETS_US

# ---------------------------------------------------------------------------
# span phases (the causal chain of one client command)
# ---------------------------------------------------------------------------

SUBMIT = "submit"        # client session issued the command
ENQUEUE = "enqueue"      # proxy queued it for the consensus step
APPEND = "append"        # leader appended it — stamped (term, index)
QUORUM = "quorum"        # majority acked: the LEADER's commit covers it
COMMIT = "commit"        # a replica's commit index covers it
APPLY = "apply"          # a replica's host apply covers it
ACK = "ack"              # client ack released
RETRANSMIT = "retransmit"  # the same (conn, req) was re-submitted
FAIL = "fail"            # terminal failure mark

# ordered critical-path phases (per-replica COMMIT marks are evidence,
# not client-visible latency; APPLY uses the origin replica's mark)
CP_PHASES = (SUBMIT, ENQUEUE, APPEND, QUORUM, APPLY, ACK)

# terminal statuses
OPEN = "open"            # still in flight (or never resolved)
DONE = "done"            # acked to the client
FAILOVER = "failover"    # failed at deposition / step-down / stop

DEFAULT_SAMPLE_EVERY = 64
DEFAULT_CAPACITY = 4096

# runtime override for the sampling rate: every recorder built without
# an explicit ``sample_every`` (the driver, the sharded driver, the
# RP_GOVERNOR daemon — all construct a default Observability) honors
# it. 0 disables tracing entirely; garbage falls back to the default.
SAMPLE_ENV = "RP_TRACE_SAMPLE"


def default_sample_every() -> int:
    raw = os.environ.get(SAMPLE_ENV)
    if raw is None:
        return DEFAULT_SAMPLE_EVERY
    try:
        return max(0, int(raw))
    except ValueError:
        return DEFAULT_SAMPLE_EVERY


def span_trace_id(conn: int, req: int) -> str:
    """The stable external id of one command span — what exemplars
    carry and what ``obs blame``/Perfetto label spans as."""
    return f"c{conn}/r{req}"


class _Span:
    """One sampled command's causal record (host bookkeeping only)."""

    __slots__ = ("conn", "req", "origin", "term", "index", "leader",
                 "group", "status", "retransmits", "pending_marks",
                 "events")

    def __init__(self, conn: int, req: int, origin: int):
        self.conn = conn
        self.req = req
        self.origin = origin           # replica the command entered at
        self.term: Optional[int] = None
        self.index: Optional[int] = None
        self.leader: Optional[int] = None
        self.group = -1                # consensus group (-1: unsharded)
        self.status = OPEN
        self.retransmits = 0
        # commit+apply marks still expected (2 per correlated replica);
        # a DONE span retires once they all arrive
        self.pending_marks = 0
        self.events: List[Tuple[str, int, float]] = []  # (phase, rep, ts)

    def as_dict(self) -> dict:
        d = dict(conn=self.conn, req=self.req, origin=self.origin,
                 term=self.term, index=self.index, leader=self.leader,
                 status=self.status, retransmits=self.retransmits,
                 events=[[p, r, t] for (p, r, t) in self.events])
        if self.group >= 0:
            # sharded spans carry their group; unsharded dumps keep the
            # pre-sharding schema byte-for-byte (golden-file pinned)
            d["group"] = self.group
        return d


class SpanRecorder:
    """Thread-safe, bounded, sampled recorder of command spans.

    Keys: a command is identified by ``(conn, req)`` — the driver's
    globally-unique connection id + per-replica submit sequence, or a
    KVS session's ``(client_id, req_id)`` stamp. A retransmit reuses
    the key, so it lands on the SAME span (it is the same logical
    command).

    Frontier marks are O(log open-spans) via per-replica heaps:
    ``commit_advance(r, n)`` / ``apply_advance(r, n)`` pop every
    sampled span whose stamped absolute index is below the frontier.
    Indices are ABSOLUTE (rebase-corrected): callers add their
    ``rebased_total`` so i32 rollovers never tear a span.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 sample_every: Optional[int] = None,
                 clock=time.monotonic):
        self.capacity = capacity
        if sample_every is None:
            # resolved at construction (not import) so a test/daemon
            # that sets RP_TRACE_SAMPLE after import still wins
            sample_every = default_sample_every()
        self.sample_every = max(0, int(sample_every))  # 0 = disabled
        self._clock = clock
        self._lock = threading.Lock()
        self._counter = 0                  # commands seen (sampling)
        self._open: Dict[Tuple[int, int], _Span] = {}
        self._done: collections.deque = collections.deque(maxlen=capacity)
        # acked spans still awaiting commit/apply marks (FIFO): a
        # permanently-stopped replica's frontier never advances, so at
        # capacity the oldest of these is force-retired — the client
        # already has its ack; the missing marks ARE the evidence —
        # instead of wedging the recorder for the process lifetime
        self._done_pending: "collections.OrderedDict" = \
            collections.OrderedDict()
        self.dropped = 0                   # samples refused at capacity
        # (group, term, index) -> key, for cross-replica correlation
        # queries (group -1 = unsharded single-group callers)
        self._by_ti: Dict[Tuple[int, int, int], Tuple[int, int]] = {}
        # per-replica frontier heaps: (abs_index, key)
        self._await_commit: Dict[int, list] = {}
        self._await_apply: Dict[int, list] = {}
        # per-origin-replica ack matching: (req, key) — the driver
        # releases acks by monotone submit sequence
        self._await_ack: Dict[int, list] = {}
        # cheap read-span variant (runtime/reads.py): completed
        # lease/read-index reads as (replica, path, t0, t1) records —
        # no correlation machinery, own sampling counter so read
        # traffic can never shift which COMMANDS get sampled
        self._reads: collections.deque = collections.deque(
            maxlen=capacity)
        self._read_counter = 0

    # ---------------- cheap-path predicates ----------------

    @property
    def enabled(self) -> bool:
        return self.sample_every > 0

    @property
    def open_count(self) -> int:
        return len(self._open)

    def set_sample_every(self, n: int) -> None:
        """1 = trace every command (``--trace``); 0 = off."""
        self.sample_every = max(0, int(n))

    def resize(self, capacity: int) -> None:
        """Grow/shrink the retained-span bound (``--trace`` runs size
        it to the whole workload so the export misses nothing)."""
        with self._lock:
            self.capacity = int(capacity)
            self._done = collections.deque(self._done,
                                           maxlen=self.capacity)

    # ---------------- recording ----------------

    def begin(self, conn: int, req: int, replica: int,
              phase: str = ENQUEUE) -> bool:
        """A command entered the system; returns True iff sampled.
        Re-entering an already-open key records a retransmit on the
        existing span (same logical command)."""
        if not self.sample_every:
            return False
        with self._lock:
            key = (conn, req)
            sp = self._open.get(key)
            if sp is not None:
                sp.retransmits += 1
                sp.events.append((RETRANSMIT, replica, self._clock()))
                return True
            self._counter += 1
            if (self._counter - 1) % self.sample_every:
                return False
            if len(self._open) >= self.capacity:
                if self._done_pending:
                    # evict the oldest acked-but-unmarked span rather
                    # than refusing every future sample
                    old_key, _ = self._done_pending.popitem(last=False)
                    old_sp = self._open.get(old_key)
                    if old_sp is not None:
                        self._retire_locked(old_key, old_sp)
                else:
                    self.dropped += 1
                    return False
            sp = _Span(conn, req, replica)
            sp.events.append((phase, replica, self._clock()))
            self._open[key] = sp
            h = self._await_ack.setdefault(replica, [])
            heapq.heappush(h, (req, key))
            if len(h) > 4 * self.capacity:
                self._compact_locked(h)     # direct-key acks bypass it
            return True

    def mark(self, conn: int, req: int, phase: str,
             replica: int = -1) -> None:
        """Stamp a phase on an open sampled span (no-op otherwise)."""
        if not self._open:
            return
        with self._lock:
            sp = self._open.get((conn, req))
            if sp is not None:
                sp.events.append((phase, replica, self._clock()))

    def stamp_append(self, conn: int, req: int, term: int, index: int,
                     leader: int,
                     replicas: Sequence[int] = (),
                     group: int = -1) -> None:
        """The leader appended this command at absolute ``index`` in
        ``term`` — the cross-replica correlation key. ``replicas``
        lists the replica ids whose commit/apply frontiers this
        process observes (all of them in-process; just the local one
        for a NodeDaemon); the span retires once each has both marks
        (plus the client ack). A second append of the same key (a
        committed duplicate from a retransmit) is recorded but the
        FIRST (term, index) wins — first-commit order is the one the
        state machine deduplicates to.

        ``group`` namespaces the correlation key for sharded clusters:
        ``(term, index)`` is unique within ONE consensus group but G
        independent groups number terms and indices identically, so
        the full key is ``(group, term, index)`` (-1 for unsharded
        callers — the legacy key, unchanged)."""
        if not self._open:
            return
        with self._lock:
            sp = self._open.get((conn, req))
            if sp is None:
                return
            ts = self._clock()
            if sp.term is not None:
                sp.retransmits += 1
                sp.events.append((RETRANSMIT, leader, ts))
                return
            sp.term, sp.index, sp.leader = int(term), int(index), leader
            sp.group = int(group)
            sp.events.append((APPEND, leader, ts))
            key = (conn, req)
            self._by_ti[(sp.group, sp.term, sp.index)] = key
            sp.pending_marks = 2 * len(replicas)
            for r in replicas:
                hc = self._await_commit.setdefault(r, [])
                ha = self._await_apply.setdefault(r, [])
                heapq.heappush(hc, (sp.index, key))
                heapq.heappush(ha, (sp.index, key))
                if len(hc) > 4 * self.capacity:
                    # a frontier that never advances (partitioned
                    # replica) must not accumulate retired spans' stale
                    # entries without bound
                    self._compact_locked(hc)
                    self._compact_locked(ha)

    def _compact_locked(self, heap: list) -> None:
        live = [(i, k) for (i, k) in heap if k in self._open]
        heapq.heapify(live)
        heap[:] = live

    def _frontier(self, heaps: Dict[int, list], replica: int,
                  upto: int, phase: str) -> None:
        h = heaps.get(replica)
        if not h:
            return
        with self._lock:
            ts = self._clock()
            while h and h[0][0] < upto:
                idx, key = heapq.heappop(h)
                sp = self._open.get(key)
                if sp is None or sp.index != idx:
                    continue               # retired / superseded entry
                sp.events.append((phase, replica, ts))
                if phase == COMMIT and replica == sp.leader:
                    # the leader's commit advance IS the quorum ack
                    sp.events.append((QUORUM, replica, ts))
                sp.pending_marks -= 1
                if sp.pending_marks <= 0 and sp.status == DONE:
                    self._retire_locked(key, sp)

    def commit_advance(self, replica: int, upto: int) -> None:
        """Replica ``replica``'s commit frontier reached ``upto``
        (absolute count: indices < upto are committed)."""
        self._frontier(self._await_commit, replica, upto, COMMIT)

    def apply_advance(self, replica: int, upto: int) -> None:
        self._frontier(self._await_apply, replica, upto, APPLY)

    def ack_release(self, replica: int,
                    upto_req: int) -> List[Tuple[int, int]]:
        """The driver released client acks on ``replica`` for every
        submit sequence <= ``upto_req``. Returns the ``(conn, req)``
        keys of the SAMPLED spans acked by this call — the driver's
        latency observe attaches histogram exemplars only to those."""
        h = self._await_ack.get(replica)
        if not h:
            return []
        acked: List[Tuple[int, int]] = []
        with self._lock:
            ts = self._clock()
            while h and h[0][0] <= upto_req:
                req, key = heapq.heappop(h)
                sp = self._open.get(key)
                if sp is None:
                    continue
                sp.events.append((ACK, replica, ts))
                sp.status = DONE
                acked.append(key)
                if sp.pending_marks <= 0:
                    self._retire_locked(key, sp)
                else:
                    self._done_pending[key] = None
        return acked

    def ack_key(self, conn: int, req: int) -> None:
        """Direct-key client ack (KVS sessions, which observe commit
        through the dedup high-water mark rather than a driver seq)."""
        if not self._open:
            return
        with self._lock:
            key = (conn, req)
            sp = self._open.get(key)
            if sp is None:
                return
            sp.events.append((ACK, sp.origin, self._clock()))
            sp.status = DONE
            if sp.pending_marks <= 0:
                self._retire_locked(key, sp)
            else:
                self._done_pending[key] = None

    def fail_open(self, replica: int, status: str = FAILOVER) -> int:
        """Close EVERY open span awaiting ack on ``replica`` with a
        terminal ``status`` — the leader-failover path: when the
        driver fails its inflight waiters (deposition, step-down,
        stop), their spans must terminate too, never leak. Returns the
        number closed."""
        h = self._await_ack.get(replica)
        if not h:
            return 0
        n = 0
        with self._lock:
            ts = self._clock()
            while h:
                _, key = heapq.heappop(h)
                sp = self._open.get(key)
                if sp is None:
                    continue
                sp.events.append((FAIL, replica, ts))
                sp.status = status
                self._retire_locked(key, sp)
                n += 1
        return n

    def fail_key(self, conn: int, req: int, status: str = FAILOVER,
                 replica: int = -1) -> None:
        if not self._open:
            return
        with self._lock:
            key = (conn, req)
            sp = self._open.get(key)
            if sp is None:
                return
            sp.events.append((FAIL, replica, self._clock()))
            sp.status = status
            self._retire_locked(key, sp)

    def _retire_locked(self, key, sp: _Span) -> None:
        self._open.pop(key, None)
        self._done_pending.pop(key, None)
        if sp.term is not None:
            self._by_ti.pop((sp.group, sp.term, sp.index), None)
        self._done.append(sp)

    # ---------------- queries / export ----------------

    def read_span(self, replica: int, path: str, t0: float, *,
                  group: int = -1, status: str = DONE) -> Optional[str]:
        """Record one served linearizable READ as a lightweight span
        (sampled like commands, but on a separate counter): the read
        critical path is just [enqueue, serve] on the serving replica
        — no append/commit/apply correlation to carry. Rendered as
        duration slices on a dedicated reads track by
        :func:`to_chrome_trace`. Returns the read's trace id when
        sampled (truthy, so pre-existing boolean callers still work),
        None otherwise — the id feeds the read-latency histogram's
        exemplar."""
        if not self.sample_every:
            return None
        with self._lock:
            self._read_counter += 1
            if (self._read_counter - 1) % self.sample_every:
                return None
            rid = f"read-{self._read_counter - 1}"
            self._reads.append(dict(replica=int(replica), path=path,
                                    t0=float(t0), t1=self._clock(),
                                    group=int(group), status=status,
                                    id=rid))
            return rid

    def key_for(self, term: int, index: int,
                group: int = -1) -> Optional[Tuple[int, int]]:
        with self._lock:
            return self._by_ti.get((int(group), int(term), int(index)))

    def counts(self) -> dict:
        with self._lock:
            by_status: Dict[str, int] = {}
            for sp in self._done:
                by_status[sp.status] = by_status.get(sp.status, 0) + 1
            return dict(open=len(self._open), done=len(self._done),
                        dropped=self.dropped, sampled=by_status)

    def dump(self, anchor: Optional[dict] = None) -> dict:
        """Point-in-time span dump: plain data, JSON-serializable,
        stamped with the shared clock anchor so multi-process dumps
        align on one timebase. Open spans are included as-is (status
        ``open``)."""
        with self._lock:
            spans = ([sp.as_dict() for sp in self._done]
                     + [sp.as_dict() for sp in self._open.values()])
            reads = [dict(r) for r in self._reads]
        out = dict(schema=1,
                   anchor=anchor if anchor is not None else clock_anchor(),
                   sample_every=self.sample_every,
                   dropped=self.dropped, spans=spans)
        if reads:
            # only when read spans exist: dumps from read-free runs
            # keep the pre-reads schema byte-for-byte (golden-pinned)
            out["reads"] = reads
        return out

    def write_json(self, path: str) -> str:
        import os
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.dump(), f, indent=2)
        os.replace(tmp, path)
        return path

    def reset(self) -> None:
        with self._lock:
            self._open.clear()
            self._done.clear()
            self._by_ti.clear()
            self._await_commit.clear()
            self._await_apply.clear()
            self._await_ack.clear()
            self._done_pending.clear()
            self._reads.clear()
            self._read_counter = 0
            self._counter = 0
            self.dropped = 0


def active_recorder(obs) -> Optional[SpanRecorder]:
    """The facade's span recorder iff tracing is enabled — the ONE
    enablement probe every integration point (sim, KVS, ...) shares,
    so the predicate can never diverge between paths."""
    if obs is None:
        return None
    sp = getattr(obs, "spans", None)
    return sp if (sp is not None and sp.enabled) else None


# ---------------------------------------------------------------------------
# step-phase profiler
# ---------------------------------------------------------------------------

# the attributable hot-loop phases (one histogram series per phase)
PHASE_HOST_ENCODE = "host_encode"        # batch pack / input build
PHASE_DEVICE_DISPATCH = "device_dispatch"  # program enqueue (async)
PHASE_DEVICE_SYNC = "device_sync"        # explicit fence (opt-in)
PHASE_QUORUM_WAIT = "quorum_wait"        # blocking commit readback
PHASE_APPLY = "apply"                    # committed-window replay
PHASE_ACK_RELEASE = "ack_release"        # waiter release + latency obs
PHASE_APPLY_REPLAY_ACK = "apply_replay_ack"  # driver store/replay/ack
                                         # sweep (whole-batch, per
                                         # replica) — the host_path
                                         # A/B attribution phase


class StepPhaseProfiler:
    """Wall-time phase attribution for the driver/daemon hot loops.

    Without fencing (the default), ``device_dispatch`` measures program
    ENQUEUE under async dispatch and the device time surfaces wherever
    the host first blocks on results (``quorum_wait``) — the honest
    shape of a pipelined driver, and exactly what the pre-existing
    ``step_latency_us`` conflated. With ``fence=True``, :meth:`sync`
    blocks on the step's outputs immediately after dispatch, so device
    time lands in its own ``device_sync`` series and ``quorum_wait``
    shrinks to the readback. Fencing serializes the dispatch pipeline —
    it is a profiling mode, off by default, and changes no compiled
    programs (cache-key guarded).
    """

    BUCKETS_US = LATENCY_BUCKETS_US
    PHASES = (PHASE_HOST_ENCODE, PHASE_DEVICE_DISPATCH,
              PHASE_DEVICE_SYNC, PHASE_QUORUM_WAIT, PHASE_APPLY,
              PHASE_ACK_RELEASE, PHASE_APPLY_REPLAY_ACK)

    def __init__(self, metrics=None, *, fence: bool = False,
                 replica: int = -1):
        self.metrics = metrics           # MetricsRegistry or None
        self.fence = fence
        self.replica = replica
        self.acc: Dict[str, Tuple[int, float, float]] = {}
        self._open: Dict[str, int] = {}
        # opt-in timestamped phase slices (enable_events): the
        # host-phase TRACK of the merged device timeline
        # (obs.device.merge_timeline) — histograms alone cannot place
        # a phase on a wall-clock axis
        self.events: Optional[collections.deque] = None

    def enable_events(self, capacity: int = 65536) -> None:
        """Record ``(phase, t0_monotonic, t1_monotonic)`` triples in a
        bounded ring alongside the histograms (off by default — one
        extra clock read per stop)."""
        self.events = collections.deque(maxlen=capacity)

    def start(self, phase: str) -> None:
        self._open[phase] = time.perf_counter_ns()

    def stop(self, phase: str) -> None:
        t0 = self._open.pop(phase, None)
        if t0 is None:
            return
        us = (time.perf_counter_ns() - t0) / 1e3
        n, tot, mx = self.acc.get(phase, (0, 0.0, 0.0))
        self.acc[phase] = (n + 1, tot + us, max(mx, us))
        if self.events is not None:
            t1m = time.monotonic()
            self.events.append((phase, t1m - us / 1e6, t1m))
        if self.metrics is not None:
            self.metrics.observe("step_phase_us", us,
                                 buckets=self.BUCKETS_US, phase=phase,
                                 replica=self.replica)

    def sync(self, outputs) -> None:
        """Explicit device fence: block until ``outputs`` are ready,
        timed as ``device_sync``. NO-OP unless fencing is enabled —
        the default path never blocks here (and never imports JAX)."""
        if not self.fence:
            return
        import jax                        # deliberate lazy import
        self.start(PHASE_DEVICE_SYNC)
        jax.block_until_ready(outputs)
        self.stop(PHASE_DEVICE_SYNC)

    def sums(self) -> Dict[str, dict]:
        """Per-phase ``{n, total_us, max_us}`` sums with zero-sample
        phases SUPPRESSED — the one exporter benches embed in their
        detail rows, so A/B tables never carry dead columns (e.g. a
        ``device_sync`` row when ``fence=`` is off)."""
        return {p: dict(n=a[0], total_us=round(a[1], 1),
                        max_us=round(a[2], 1))
                for p, a in sorted(self.acc.items()) if a[0] > 0}

    def report(self) -> str:
        lines = []
        for phase, (n, tot, mx) in sorted(self.acc.items()):
            if n == 0:
                continue          # zero-sample phases carry no signal
            lines.append(f"{phase}: n={n} mean={tot / max(n, 1):.1f}us "
                         f"max={mx:.1f}us")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto-loadable)
# ---------------------------------------------------------------------------

CP_PID = 9999            # the critical-path pseudo-process
READS_PID = 9998         # the lease/read-index read-span pseudo-process


def _span_label(sp: dict) -> str:
    label = "c%d/r%d" % (sp["conn"], sp["req"])
    if sp.get("term") is not None:
        label += " (t%d,i%d)" % (sp["term"], sp["index"])
    return label


def _critical_path(sp: dict, wall) -> List[Tuple[str, float, float]]:
    """-> ordered (segment, t0_wall, t1_wall) list for one span: the
    client-visible chain over whichever CP phases were observed."""
    marks: Dict[str, float] = {}
    for phase, rep, ts in sp["events"]:
        if phase not in CP_PHASES:
            continue
        if phase == APPLY and rep != sp["origin"] and APPLY in marks:
            continue                      # prefer the origin's apply
        if phase in marks and phase != APPLY:
            continue                      # first mark wins
        marks[phase] = wall(ts)
    chain = [(p, marks[p]) for p in CP_PHASES if p in marks]
    return [(f"{a}->{b}", ta, tb)
            for (a, ta), (b, tb) in zip(chain, chain[1:])]


def to_chrome_trace(dumps, *, max_cp_tracks: int = 512,
                    t0_wall: Optional[float] = None) -> dict:
    """Merge one or more span dumps into a Chrome trace-event JSON
    object (Perfetto-loadable): per-replica tracks carry instant
    phase marks correlated by ``(term, index)``; each sampled command
    additionally gets a critical-path track of duration slices.
    Dumps from different processes are aligned via their stamped
    clock anchors. ``t0_wall`` overrides the computed timeline epoch —
    the hook ``obs.device.merge_timeline`` uses to fold host-phase and
    device-profiler tracks onto the SAME axis (and the only caller for
    which the epoch lands in ``otherData``)."""
    if isinstance(dumps, dict):
        dumps = [dumps]
    walls: List[float] = []
    prepared = []
    for d in dumps:
        a = d["anchor"]

        def wall(ts, _a=a):
            return _a["wall"] + (ts - _a["monotonic"])

        for sp in d["spans"]:
            walls.extend(wall(ts) for _, _, ts in sp["events"])
        for rd in d.get("reads", ()):
            walls.append(wall(rd["t0"]))
        prepared.append((d, wall))
    t0 = (t0_wall if t0_wall is not None
          else (min(walls) if walls else 0.0))

    def us(w):
        return round((w - t0) * 1e6, 3)

    events: List[dict] = []
    replicas_seen = set()
    cp_tid = 0
    for d, wall in prepared:
        for sp in d["spans"]:
            label = _span_label(sp)
            args = dict(conn=sp["conn"], req=sp["req"],
                        origin=sp["origin"], term=sp.get("term"),
                        index=sp.get("index"), status=sp["status"],
                        retransmits=sp.get("retransmits", 0))
            for phase, rep, ts in sp["events"]:
                pid = rep if rep >= 0 else sp["origin"]
                replicas_seen.add(pid)
                events.append(dict(
                    name=f"{phase} {label}", ph="i", s="p",
                    ts=us(wall(ts)), pid=pid, tid=0, args=args))
            if cp_tid < max_cp_tracks:
                segs = _critical_path(sp, wall)
                if segs:
                    cp_tid += 1
                    events.append(dict(
                        name="thread_name", ph="M", pid=CP_PID,
                        tid=cp_tid, args=dict(name=label)))
                    for seg, ta, tb in segs:
                        events.append(dict(
                            name=seg, ph="X", ts=us(ta),
                            dur=round(max(tb - ta, 0.0) * 1e6, 3),
                            pid=CP_PID, tid=cp_tid, args=args))
    n_reads = 0
    for d, wall in prepared:
        for rd in d.get("reads", ()):
            # the read critical path is one slice: enqueue→serve on
            # the serving replica's reads track
            n_reads += 1
            ta, tb = wall(rd["t0"]), wall(rd["t1"])
            events.append(dict(
                name=f"read:{rd['path']}", ph="X", ts=us(ta),
                dur=round(max(tb - ta, 0.0) * 1e6, 3),
                pid=READS_PID, tid=rd["replica"],
                args=dict(replica=rd["replica"], path=rd["path"],
                          group=rd.get("group", -1),
                          status=rd.get("status"))))
    meta = [dict(name="process_name", ph="M", pid=r, tid=0,
                 args=dict(name=f"replica {r}"))
            for r in sorted(replicas_seen)]
    meta.append(dict(name="process_name", ph="M", pid=CP_PID, tid=0,
                     args=dict(name="critical path")))
    if n_reads:
        meta.append(dict(name="process_name", ph="M", pid=READS_PID,
                         tid=0, args=dict(name="reads")))
    other = dict(tool="rdma_paxos_tpu.obs.spans",
                 dumps=len(prepared),
                 spans=sum(len(d["spans"]) for d, _ in prepared))
    if t0_wall is not None:
        # only explicit-epoch callers carry it: the default export
        # stays byte-identical (golden-file pinned)
        other["t0_wall"] = t0
    return dict(traceEvents=meta + events, displayTimeUnit="ms",
                otherData=other)


# ---------------------------------------------------------------------------
# critical-path breakdown
# ---------------------------------------------------------------------------

def breakdown(dumps) -> dict:
    """Aggregate critical-path segment durations over every span in
    ``dumps``: per segment n/mean/p50/p95/p99 µs, plus span status
    counts — the "where did the time go" table."""
    if isinstance(dumps, dict):
        dumps = [dumps]
    segs: Dict[str, List[float]] = {}
    status: Dict[str, int] = {}
    for d in dumps:
        a = d["anchor"]

        def wall(ts, _a=a):
            return _a["wall"] + (ts - _a["monotonic"])

        for sp in d["spans"]:
            status[sp["status"]] = status.get(sp["status"], 0) + 1
            for seg, ta, tb in _critical_path(sp, wall):
                segs.setdefault(seg, []).append((tb - ta) * 1e6)
    out = dict(spans=status, segments={})
    for seg, vals in segs.items():
        vals.sort()
        n = len(vals)
        out["segments"][seg] = dict(
            n=n, mean_us=round(sum(vals) / n, 2),
            p50_us=round(vals[n // 2], 2),
            p95_us=round(vals[int(n * .95)], 2),
            p99_us=round(vals[min(int(n * .99), n - 1)], 2))
    return out


def format_breakdown(bd: dict) -> str:
    lines = ["spans: " + ", ".join(f"{k}={v}"
                                   for k, v in sorted(bd["spans"].items()))]
    order = [f"{a}->{b}" for a, b in zip(CP_PHASES, CP_PHASES[1:])]
    segs = bd["segments"]
    width = max([len(s) for s in segs] or [8])
    lines.append(f"{'segment'.ljust(width)}  {'n':>7} {'mean_us':>10} "
                 f"{'p50_us':>10} {'p95_us':>10} {'p99_us':>10}")
    for seg in sorted(segs, key=lambda s: (order.index(s)
                                           if s in order else 99, s)):
        st = segs[seg]
        lines.append(f"{seg.ljust(width)}  {st['n']:>7} "
                     f"{st['mean_us']:>10.2f} {st['p50_us']:>10.2f} "
                     f"{st['p95_us']:>10.2f} {st['p99_us']:>10.2f}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI: dump / merge / report
# ---------------------------------------------------------------------------

def _load_dumps(paths: Sequence[str]) -> List[dict]:
    dumps = []
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        if "spans" not in doc:
            raise SystemExit(f"{p}: not a span dump (no 'spans' key)")
        dumps.append(doc)
    return dumps


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rdma_paxos_tpu.obs.spans",
        description="Merge span dumps into a Perfetto-loadable Chrome "
                    "trace and print critical-path breakdowns.")
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, doc in (("merge", "merge one or more (multi-replica) raw "
                                "span dumps into ONE Chrome trace-event "
                                "JSON, aligned on the shared clock "
                                "anchors — open the output in "
                                "https://ui.perfetto.dev"),
                      ("dump", "alias of merge (single-file convert)")):
        p = sub.add_parser(name, help=doc)
        p.add_argument("files", nargs="+", help="raw span dump JSONs")
        p.add_argument("-o", "--out", required=True,
                       help="Chrome trace JSON output path")
    rp = sub.add_parser("report", help="print the aggregated "
                        "critical-path breakdown of span dumps")
    rp.add_argument("files", nargs="+")
    args = ap.parse_args(argv)

    dumps = _load_dumps(args.files)
    if args.cmd in ("merge", "dump"):
        trace = to_chrome_trace(dumps)
        with open(args.out, "w") as f:
            json.dump(trace, f)
        n = trace["otherData"]["spans"]
        print(f"wrote {args.out}: {len(trace['traceEvents'])} events "
              f"from {n} spans across {len(dumps)} dump(s) — load it "
              f"in https://ui.perfetto.dev")
    else:
        print(format_breakdown(breakdown(dumps)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
