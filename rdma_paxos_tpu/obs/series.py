"""Time-series retention — bounded per-series rings sampled from the
metrics registry.

The registry (obs/metrics.py) is a point-in-time surface: counters
only ever grow, gauges hold the last value, histograms accumulate
forever. Operating a long-running fleet needs the TIME dimension —
"how fast is this counter moving *now*", "what fraction of the last
minute's commits blew the latency budget" — which is exactly what the
window-domain alert rules (``rate_window`` / ``burn_rate`` in
obs/alerts.py) and the fleet console consume. This module is that
retention layer: a :class:`TimeSeriesStore` samples a registry
snapshot on the existing alert cadence into bounded per-series rings
of ``(step_index, wall, value)`` points.

Per-sample transformation (one point per series per call):

* **counters** — the point's ``value`` is the WINDOWED RATE over the
  sampling interval (``delta / dt`` per second); the raw cumulative
  total rides along (4th tuple slot) so window deltas stay exact.
* **gauges** — last value, as-is.
* **histograms** — decomposed into sub-series under the parent key:
  ``|p50`` / ``|p99`` quantile points (bucket-upper-bound estimate),
  ``|count`` / ``|sum`` cumulative (counter-shaped, rate + cum), and
  one ``|le|<bound>`` cumulative series per finite bucket bound (the
  CDF counts the burn-rate SLO rules difference over their windows).

Every store is stamped with the process's shared ``(monotonic, wall)``
anchor pair (obs/clock.py) and — when given a ``path`` — persists each
sample as ONE append-only JSONL line, so merging series from N hosts
is a file concat: every line carries its ``src`` tag and the loader
(:func:`read_jsonl` / :func:`merge_docs`) groups by it.

Stdlib only, host-side only: nothing here may run inside jitted
device code (the jit-safety scan in tests/test_ops_plane.py covers
this module), and attaching a store changes no compiled program and
no STEP_CACHE key.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from rdma_paxos_tpu.obs.clock import anchor as clock_anchor
from rdma_paxos_tpu.obs.metrics import parse_key

SCHEMA = 1

# histogram quantiles exported as sub-series points
QUANTILES: Tuple[float, ...] = (0.5, 0.99)

# sub-key separator — never appears in metric names or rendered label
# pairs, so ``key.partition("|")`` recovers the parent registry key
SUB = "|"


def split_series_key(key: str) -> Tuple[str, Dict[str, str], str]:
    """``"name{k=v}|le|0.25"`` -> ``("name", {"k": "v"}, "le|0.25")``
    — the parent metric name, its label pairs, and the sub-series
    suffix (empty for plain counter/gauge series)."""
    parent, _, sub = key.partition(SUB)
    base, pairs = parse_key(parent)
    return base, dict(pairs), sub


def _hist_quantile(h: dict, q: float) -> Optional[float]:
    """Upper bound of the bucket containing the q-th observation of
    ONE histogram dict (the obs/alerts.py estimate, single-histogram
    form)."""
    total = h["count"]
    if total == 0:
        return None
    need = q * total
    cum = 0
    for bound, c in h["buckets"].items():
        if bound == "+Inf":
            continue
        cum += c
        if cum >= need:
            return float(bound)
    return float("inf")


class TimeSeriesStore:
    """Bounded per-series rings of ``(step, wall, value, cum)`` points
    sampled from registry snapshots; optionally persisted as
    append-only JSONL."""

    def __init__(self, capacity: int = 512, path: Optional[str] = None,
                 source: str = "proc"):
        if capacity < 2:
            raise ValueError("capacity must be >= 2 (window math needs "
                             "at least two points)")
        self.capacity = int(capacity)
        self.source = source
        self.path = path
        self.anchor = clock_anchor()
        self.samples = 0
        self._lock = threading.Lock()
        self._series: Dict[str, collections.deque] = {}
        self._last_wall: Optional[float] = None
        self._last_step: int = 0
        self._fh = None
        if path is not None:
            # append-only by contract: a restarted process (or a second
            # store on the same path) extends the log, never rewrites
            # it. A missing/unwritable workdir costs the LOG, never the
            # caller — retention keeps working in memory (the drivers'
            # "observability I/O must never kill the data path" rule;
            # before this store, all workdir I/O was lazy + tolerated).
            try:
                self._fh = open(path, "a", buffering=1)
                self._fh.write(json.dumps(dict(
                    kind="header", schema=SCHEMA, src=self.source,
                    anchor=self.anchor, capacity=self.capacity)) + "\n")
            except OSError:
                self._fh = None

    # ---------------- recording ----------------

    def _push(self, key: str, step: int, wall: float, value: float,
              cum: Optional[float]) -> None:
        ring = self._series.get(key)
        if ring is None:
            ring = collections.deque(maxlen=self.capacity)
            self._series[key] = ring
        ring.append((step, wall, value, cum))

    def _counter_point(self, key: str, step: int, wall: float,
                       cum: float) -> None:
        ring = self._series.get(key)
        rate = 0.0
        if ring:
            _, pw, _, pc = ring[-1]
            dt = wall - pw
            if dt > 0 and pc is not None:
                rate = max(0.0, (cum - pc) / dt)
        self._push(key, step, wall, rate, cum)

    def sample(self, snap: dict, *, step: int,
               wall: Optional[float] = None) -> int:
        """Record one point per live series from a registry
        ``snapshot()`` dict; returns the number of series touched.
        ``wall`` is injectable for deterministic tests — production
        callers omit it."""
        wall = time.time() if wall is None else float(wall)
        step = int(step)
        n = 0
        row: Dict[str, object] = {}
        with self._lock:
            for key, v in snap["counters"].items():
                self._counter_point(key, step, wall, float(v))
                row[key] = [self._series[key][-1][2], float(v)]
                n += 1
            for key, v in snap["gauges"].items():
                self._push(key, step, wall, float(v), None)
                row[key] = float(v)
                n += 1
            for key, h in snap["histograms"].items():
                for q in QUANTILES:
                    est = _hist_quantile(h, q)
                    if est is not None:
                        sk = f"{key}{SUB}p{int(q * 100)}"
                        self._push(sk, step, wall, est, None)
                        row[sk] = est
                        n += 1
                for sk, cum in ((f"{key}{SUB}count", float(h["count"])),
                                (f"{key}{SUB}sum", float(h["sum"]))):
                    self._counter_point(sk, step, wall, cum)
                    row[sk] = [self._series[sk][-1][2], cum]
                    n += 1
                running = 0.0
                for bound, c in h["buckets"].items():
                    if bound == "+Inf":
                        continue
                    running += c
                    sk = f"{key}{SUB}le{SUB}{bound}"
                    self._counter_point(sk, step, wall, running)
                    row[sk] = [self._series[sk][-1][2], running]
                    n += 1
            self.samples += 1
            self._last_wall = wall
            self._last_step = step
            fh = self._fh
        if fh is not None:
            try:
                fh.write(json.dumps(dict(
                    kind="sample", src=self.source, step=step,
                    wall=wall, points=row)) + "\n")
            except (OSError, ValueError):
                pass    # retention I/O must never kill the caller
        return n

    # ---------------- reading ----------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def points(self, key: str) -> List[Tuple[int, float, float]]:
        """Retained ``(step, wall, value)`` points, oldest first."""
        with self._lock:
            ring = self._series.get(key)
            return [(s, w, v) for (s, w, v, _c) in ring] if ring else []

    def latest(self, key: str) -> Optional[float]:
        with self._lock:
            ring = self._series.get(key)
            return ring[-1][2] if ring else None

    def match(self, base: str, labels: Optional[dict] = None,
              sub: str = "") -> List[str]:
        """Series keys whose parent metric is ``base``, restricted to
        exact ``labels`` pairs when given, with sub-suffix ``sub``
        (``""`` = plain counter/gauge series)."""
        out = []
        with self._lock:
            keys = list(self._series)
        for key in keys:
            b, pairs, s = split_series_key(key)
            if b != base or s != sub:
                continue
            if labels and any(pairs.get(k) != str(v)
                              for k, v in labels.items()):
                continue
            out.append(key)
        return out

    def le_bounds(self, key_prefix: str) -> List[float]:
        """The ``|le|`` bucket bounds retained for one parent series
        key (``"name{labels}"``), ascending."""
        pre = f"{key_prefix}{SUB}le{SUB}"
        with self._lock:
            bs = [float(k[len(pre):]) for k in self._series
                  if k.startswith(pre)]
        return sorted(bs)

    def _window(self, ring, *, wall_s: Optional[float],
                steps: Optional[int]):
        """-> (baseline_point, last_point) bracketing the trailing
        window, anchored at the series' LAST sample (step+wall domain
        of the data — deterministic, not the realtime clock). The
        baseline is the newest point at-or-before the window start.

        When retained history does not reach back to the window start
        there are two cases: a ring that already dropped its tail
        (saturated — full retention IS all we can know, evaluate over
        it) and a cold-start ring that simply hasn't lived that long
        yet — the latter returns None, because letting 10 s of boot
        history masquerade as a 300 s window would turn every startup
        blip into a multi-window page (the exact transient the slow
        window exists to suppress)."""
        if not ring or len(ring) < 2:
            return None
        last = ring[-1]
        if wall_s is not None:
            cutoff = last[1] - float(wall_s)
            sel = lambda p: p[1] <= cutoff           # noqa: E731
        elif steps is not None:
            cutoff = last[0] - int(steps)
            sel = lambda p: p[0] <= cutoff           # noqa: E731
        else:
            raise ValueError("window needs wall_s= or steps=")
        base = None
        for p in ring:
            if sel(p):
                base = p
            else:
                break
        if base is None:
            if len(ring) < (ring.maxlen or 0):
                return None          # cold start: too little history
            base = ring[0]           # saturated: full retention
        if base is last:
            return None
        return base, last

    def window_delta(self, key: str, *, wall_s: Optional[float] = None,
                     steps: Optional[int] = None) -> Optional[float]:
        """Cumulative-value delta over the trailing window (counter
        and histogram ``|count``/``|sum``/``|le|`` series); None for
        gauge-shaped series or too-short history."""
        with self._lock:
            ring = self._series.get(key)
            w = self._window(ring, wall_s=wall_s, steps=steps)
            if w is None:
                return None
            (_, _, _, c0), (_, _, _, c1) = w
            if c0 is None or c1 is None:
                return None
            return max(0.0, c1 - c0)

    def window_rate(self, key: str, *, wall_s: Optional[float] = None,
                    steps: Optional[int] = None) -> Optional[float]:
        """Average per-second rate over the trailing window, from the
        cumulative totals (exact — independent of sampling jitter)."""
        with self._lock:
            ring = self._series.get(key)
            w = self._window(ring, wall_s=wall_s, steps=steps)
            if w is None:
                return None
            (_, w0, _, c0), (_, w1, _, c1) = w
            if c0 is None or c1 is None or w1 <= w0:
                return None
            return max(0.0, (c1 - c0) / (w1 - w0))

    # ---------------- export ----------------

    def to_dict(self) -> dict:
        """Full retained state, JSON-serializable (the ``/series``
        endpoint body and the postmortem bundle's series section)."""
        with self._lock:
            series = {k: [[s, w, v, c] for (s, w, v, c) in ring]
                      for k, ring in sorted(self._series.items())}
        return dict(schema=SCHEMA, kind="series", src=self.source,
                    anchor=self.anchor, capacity=self.capacity,
                    samples=self.samples, series=series)

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# JSONL loading / cross-host merge (file concat IS the merge)
# ---------------------------------------------------------------------------

def read_jsonl(path: str) -> List[dict]:
    """Parse one series JSONL file (possibly a concat of several
    hosts' files — every line is self-describing); unparseable lines
    are skipped, truncated tails tolerated."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def merge_docs(lines: List[dict]) -> Dict[str, dict]:
    """Group loaded JSONL lines by source tag: ``{src: {"anchor":
    ..., "series": {key: [[step, wall, value, cum|None], ...]}}}`` —
    N hosts' concatenated logs come apart cleanly because every
    sample line names its ``src``."""
    out: Dict[str, dict] = {}
    for ln in lines:
        src = ln.get("src", "?")
        doc = out.setdefault(src, dict(anchor=None, series={}))
        if ln.get("kind") == "header":
            doc["anchor"] = ln.get("anchor")
        elif ln.get("kind") == "sample":
            step, wall = ln.get("step", 0), ln.get("wall", 0.0)
            for key, v in (ln.get("points") or {}).items():
                if isinstance(v, list):
                    rate, cum = float(v[0]), float(v[1])
                else:
                    rate, cum = float(v), None
                doc["series"].setdefault(key, []).append(
                    [step, wall, rate, cum])
    return out
