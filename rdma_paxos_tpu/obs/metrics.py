"""Process-local metrics registry — counters, gauges, fixed-bucket
histograms.

The reference's only telemetry is per-replica text logs (``debug.h``
``info_wtime`` macros) grepped by ``run.sh``; that answers "who is the
leader" but not the questions the ROADMAP's north-star demands at
production scale: commit latency distributions, replication throughput,
election churn, log-rebase headroom, replay backpressure. This registry
is the exported-signal layer those answers come from.

Design constraints (deliberate):

* **Zero dependencies** — stdlib only, importable from any layer
  (proxy, consensus host side, elastic control plane) without pulling
  in JAX or numpy.
* **Cheap enough for the driver hot loop** — one lock acquisition and
  a dict store per operation; histograms bisect a fixed bucket list.
  Instrumentation is HOST-SIDE ONLY: nothing in this module may be
  called from inside a jitted/``shard_map``ped function (verified by
  ``tests/test_obs.py`` — compiled-step cache keys are unchanged by
  instrumentation).
* **Thread-safe** — proxy link threads, the poll thread, and app
  threads all record concurrently.

Series are keyed by ``name`` plus sorted ``label=value`` pairs (the
per-replica label being the ubiquitous one); ``snapshot()`` renders
them as ``name{k=v,...}`` strings, JSON-exportable for the bench
harness and BENCH_* rounds.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

# Default bucket ladders. Latency buckets span the p99<50µs device
# frontier (BASELINE.md) up to election-timeout scale; batch buckets
# are powers of two matching slot-ring geometry.
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    1e-5, 2e-5, 5e-5, 1e-4, 2e-4, 5e-4, 1e-3, 2e-3, 5e-3,
    1e-2, 2e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)
# the single µs ladder (StepTimer sections, bench dispatch latencies):
# one definition so histograms stay comparable across BENCH_* rounds
LATENCY_BUCKETS_US: Tuple[float, ...] = (
    10, 20, 50, 100, 200, 500, 1000, 2000, 5000,
    10000, 50000, 100000, 1000000)
BATCH_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048)


def _key(name: str, labels: dict) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    return name, tuple(sorted((k, str(v)) for k, v in labels.items()))


def _render(key: Tuple[str, Tuple[Tuple[str, str], ...]]) -> str:
    name, labels = key
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"


def parse_key(key: str) -> Tuple[str, List[Tuple[str, str]]]:
    """Inverse of the rendered-key grammar: ``"name{k=v,...}"`` ->
    ``(name, [(k, v), ...])``. The ONE parser every consumer of
    rendered keys shares (alert matching, series sub-keys, Prometheus
    rendering) — the grammar lives here, next to :func:`_render`."""
    base, sep, rest = key.partition("{")
    pairs: List[Tuple[str, str]] = []
    if sep:
        for part in rest.rstrip("}").split(","):
            if part:
                k, _, v = part.partition("=")
                pairs.append((k, v))
    return base, pairs


# per-bucket exemplar reservoir bound: enough to hand a pager a few
# concrete slow traces, small enough that a histogram stays a few
# hundred bytes
EXEMPLARS_PER_BUCKET = 4


class _Hist:
    __slots__ = ("bounds", "counts", "count", "sum", "min", "max",
                 "exemplars")

    def __init__(self, bounds: Sequence[float]):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)   # +1 = overflow
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        # bucket index -> bounded [(trace_id, value)] reservoir; lazy
        # (None until the first exemplar) so exemplar-free histograms
        # cost nothing and snapshot byte-identically to before
        self.exemplars: Optional[Dict[int, list]] = None

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        b = bisect.bisect_left(self.bounds, value)
        self.counts[b] += 1
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if exemplar is not None:
            if self.exemplars is None:
                self.exemplars = {}
            res = self.exemplars.setdefault(b, [])
            if len(res) < EXEMPLARS_PER_BUCKET:
                res.append((exemplar, value))
            else:
                # deterministic replacement (no RNG): the slot cycles
                # with the observation count, so the reservoir keeps a
                # moving sample of recent exemplars per bucket
                res[self.count % EXEMPLARS_PER_BUCKET] = (exemplar,
                                                          value)

    def _bucket_label(self, b: int) -> str:
        return repr(self.bounds[b]) if b < len(self.bounds) else "+Inf"

    def as_dict(self) -> dict:
        buckets = {repr(b): c for b, c in zip(self.bounds, self.counts)}
        buckets["+Inf"] = self.counts[-1]
        out = dict(buckets=buckets, count=self.count, sum=self.sum,
                   min=(self.min if self.count else None),
                   max=(self.max if self.count else None))
        if self.exemplars:
            # only when exemplars exist: exemplar-free snapshots keep
            # the pre-exemplar schema byte-for-byte
            out["exemplars"] = {
                self._bucket_label(b): [[tid, v] for tid, v in res]
                for b, res in sorted(self.exemplars.items()) if res}
        return out


class MetricsRegistry:
    """Thread-safe counters / gauges / fixed-bucket histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict = {}
        self._gauges: Dict = {}
        self._hists: Dict = {}

    # ---------------- recording ----------------

    def inc(self, name: str, value: float = 1, **labels) -> None:
        k = _key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0) + value

    def set(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[_key(name, labels)] = value

    def observe(self, name: str, value: float,
                buckets: Optional[Sequence[float]] = None,
                exemplar: Optional[str] = None, **labels) -> None:
        """Record ``value`` into histogram ``name``. ``buckets`` fixes
        the bucket upper bounds on FIRST use of a series; later calls
        reuse the established ladder (fixed-bucket by design — merges
        and snapshots never re-bin). ``exemplar`` attaches a trace id
        to the value's bucket (bounded reservoir) — the join between a
        latency histogram and the span that produced its tail."""
        k = _key(name, labels)
        with self._lock:
            h = self._hists.get(k)
            if h is None:
                h = _Hist(buckets if buckets is not None
                          else LATENCY_BUCKETS_S)
                self._hists[k] = h
            h.observe(float(value), exemplar)

    # ---------------- reading ----------------

    def get(self, name: str, **labels):
        """Current value of a counter or gauge series (0 if absent), or
        the histogram's dict form when ``name`` is a histogram."""
        k = _key(name, labels)
        with self._lock:
            if k in self._hists:
                return self._hists[k].as_dict()
            if k in self._gauges:
                return self._gauges[k]
            return self._counters.get(k, 0)

    def snapshot(self) -> dict:
        """Point-in-time copy: ``{"counters": {...}, "gauges": {...},
        "histograms": {...}}`` with ``name{label=value,...}`` keys —
        plain data, JSON-serializable."""
        with self._lock:
            return {
                "counters": {_render(k): v
                             for k, v in sorted(self._counters.items())},
                "gauges": {_render(k): v
                           for k, v in sorted(self._gauges.items())},
                "histograms": {_render(k): h.as_dict()
                               for k, h in sorted(self._hists.items())},
            }

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent)

    def write_json(self, path: str) -> None:
        """Atomic (tmp + rename) JSON export — safe to read while the
        process keeps recording."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_json(indent=2))
        os.replace(tmp, path)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


# process-global default — the sink for module-level instrumentation
# (consensus/snapshot.py, runtime/elastic.py, proxy quiesce) that has no
# driver instance to hang a registry off
_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default
