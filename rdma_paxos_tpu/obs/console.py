"""Fleet console — a live cluster view merged from N hosts, and
one-command postmortem bundles.

Until this module, watching a deployed cluster meant tailing R
greppable replica logs and cat-ing per-replica ``*.health.json``
files by hand, and a postmortem meant collecting five
differently-shaped dump files (series JSONL, span dump, audit
artifact, trace ring, metrics snapshot). This CLI is the operator
surface over all of it:

``python -m rdma_paxos_tpu.obs.console [--once] SOURCES``
    Renders a per-group fleet table — leader, leaseholder, term,
    commit/apply frontiers, reads by path, repair/quarantine state,
    firing alerts with age — merged from any mix of sources:

    * ``--scrape http://host:port`` — a live ops exporter
      (``/healthz`` + ``/alerts``; obs/export.py), one per driver or
      NodeDaemon host;
    * ``--health PATH_OR_GLOB`` — health snapshot files
      (``replica<r>.health.json`` from N hosts, or a saved cluster
      health document).

    Default is a watch loop (``--interval`` seconds, reads/s computed
    between refreshes); ``--once`` prints a single table and exits
    (CI mode). ``--json`` emits the merged view as JSON instead.

``python -m rdma_paxos_tpu.obs.console bundle --out FILE ...``
    Assembles ONE verified postmortem artifact from a workdir
    (``--workdir`` scans the drivers' conventional file names), a
    live endpoint (``--scrape``), and/or explicit per-section flags.
    Sections: ``series`` (time-series retention), ``spans`` (causal
    command traces), ``audit`` (digest ledger artifacts), ``trace``
    (protocol event ring), ``telemetry`` (the full registry snapshot
    — every ``device_*`` series rides here), ``alerts`` (per-rule
    firing state), ``health``. Every section is sha256-manifested;
    ``bundle --verify FILE`` recomputes the digests and exits 0 iff
    the bundle is untampered AND carries the five core sections
    (series, spans, audit, telemetry, alerts).

Stdlib only (urllib for scraping) — the console must run on a bare
operator box with no accelerator stack installed; nothing here may
run inside jitted code (jit-safety-scanned).
"""

from __future__ import annotations

import argparse
import glob as _glob
import hashlib
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple
from urllib.error import HTTPError
from urllib.request import urlopen

from rdma_paxos_tpu.obs.clock import anchor as clock_anchor

BUNDLE_SCHEMA = 1
BUNDLE_KIND = "postmortem_bundle"
# the sections bundle --verify demands (trace/health ride along when
# available but their absence does not fail verification)
REQUIRED_SECTIONS = ("series", "spans", "audit", "telemetry", "alerts")

# consensus/state.py Role.LEADER — hardcoded so the console stays
# importable on a bare operator box (tests pin it against the enum)
ROLE_LEADER = 3


# ---------------------------------------------------------------------------
# source collection
# ---------------------------------------------------------------------------

def _fetch_json(url: str, timeout: float = 3.0):
    try:
        with urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except HTTPError as exc:
        # an error STATUS can still carry a JSON body — /healthz
        # answers 503 with the full health document when the poll
        # loop died, which is exactly the row the console must show
        body = exc.read().decode()
        try:
            return json.loads(body)
        except json.JSONDecodeError:
            raise exc from None


def scrape_source(base_url: str) -> dict:
    """One exporter endpoint -> a normalized source doc. ``/healthz``
    is the backbone; ``/alerts`` rides along when served (a 503
    healthz — dead poll loop — still parses: its body is the health
    document)."""
    base = base_url.rstrip("/")
    try:
        health = _fetch_json(base + "/healthz")
    except Exception as exc:            # noqa: BLE001 — a dead host is
        return dict(src=base, error=repr(exc))   # a row, not a crash
    doc = dict(src=base, health=health)
    try:
        doc["alerts"] = _fetch_json(base + "/alerts").get("state")
    except Exception:                   # noqa: BLE001
        pass
    return doc


def load_health_files(patterns: List[str]) -> List[dict]:
    out = []
    for pat in patterns:
        paths = sorted(_glob.glob(pat)) or [pat]
        for path in paths:
            try:
                with open(path) as f:
                    out.append(dict(src=path, health=json.load(f)))
            except (OSError, json.JSONDecodeError) as exc:
                out.append(dict(src=path, error=repr(exc)))
    return out


# ---------------------------------------------------------------------------
# fleet view (merge sources -> per-group rows)
# ---------------------------------------------------------------------------

def _imax(vals) -> Optional[int]:
    vals = [v for v in vals if v is not None]
    return max(int(v) for v in vals) if vals else None


def _reads_by_path(health: dict) -> Dict[str, float]:
    reads = health.get("reads") or {}
    served = reads.get("served") or {}
    return {str(k): float(v) for k, v in served.items()}


def _repair_state(health: dict) -> str:
    rep = health.get("repair")
    if not rep:
        return "-"
    active = rep.get("active") or {}
    if not active:
        n = rep.get("repairs_done", 0)
        return f"ok({n} healed)" if n else "ok"
    return ",".join(f"{k}:{st.get('phase', st.get('state', '?'))}"
                    for k, st in sorted(active.items()))


def _txn_state(health: dict) -> str:
    """Coordinator column: lifetime committed/aborted totals plus the
    in-flight count (``txn`` health entry — absent on clusters
    without a coordinator)."""
    txn = health.get("txn")
    if not txn:
        return "-"
    aborts = sum((txn.get("aborted_total") or {}).values())
    s = f"{txn.get('committed_total', 0)}c/{aborts}a"
    if txn.get("active"):
        s += f" {txn['active']}live"
    return s


def _topo_state(health: dict) -> str:
    """Elastic-topology column: current epoch + lifetime transitions,
    plus the live window phase while one is open (``topology`` health
    entry — absent on clusters without a controller)."""
    topo = health.get("topology")
    if not topo:
        return "-"
    s = f"e{topo.get('epoch', 0)}/{topo.get('transitions_total', 0)}t"
    phase = topo.get("phase")
    if phase and phase != "idle":
        s += f" {topo.get('direction', '?')}:{phase}"
    return s


def _blame_state(health: dict) -> str:
    """Critical-path blame column: the dominant latency phase at p99
    (``blame`` health entry — the tracectx blame summary, None while
    span sampling is off or no command completed)."""
    bl = health.get("blame")
    if not bl:
        return "-"
    s = f"p99:{bl.get('p99', '?')}"
    us = bl.get("p99_us")
    if us is not None:
        s += f" {us:.0f}us"
    return s


def _firing_alerts(state: Optional[dict]) -> List[dict]:
    out = []
    for name, st in (state or {}).items():
        if st.get("firing"):
            out.append(dict(name=name, severity=st.get("severity"),
                            value=st.get("value"),
                            duration_s=st.get("duration_s")))
    return sorted(out, key=lambda a: a["name"])


def fleet_view(sources: List[dict]) -> dict:
    """Merge collected source docs into the per-group fleet view:
    ``{"groups": [row...], "alerts": [...], "hosts": [...]}``.
    Cluster health documents (a driver's ``/healthz`` or a saved
    ``health()``) contribute whole groups; bare replica snapshots
    (``replica<r>.health.json`` — one file per NodeDaemon host) are
    merged into one cluster row, leader = the highest-term replica
    claiming LEADER."""
    rows: List[dict] = []
    alerts: List[dict] = []
    hosts: List[dict] = []
    members: List[Tuple[str, dict]] = []    # bare replica snapshots
    now = time.time()

    for doc in sources:
        src = doc.get("src", "?")
        if "error" in doc:
            hosts.append(dict(src=src, kind="error",
                              error=doc["error"]))
            continue
        h = doc["health"]
        age = (round(now - h["ts"], 1) if isinstance(h.get("ts"),
                                                     (int, float))
               else None)
        alerts.extend(_firing_alerts(doc.get("alerts")
                                     or h.get("alerts")))
        if isinstance(h.get("groups"), list):       # sharded cluster
            hosts.append(dict(src=src, kind="sharded", age_s=age,
                              loop_error=h.get("loop_error")))
            leases = (h.get("leases") or {}).get("holders") or []
            leaders = h.get("leaders") or []
            reads = _reads_by_path(h)
            for g, grp in enumerate(h["groups"]):
                rows.append(dict(
                    src=src, group=grp.get("group", g),
                    leader=(leaders[g] if g < len(leaders)
                            else grp.get("leader")),
                    lease=(leases[g] if g < len(leases) else None),
                    term=_imax(grp.get("term") or []),
                    commit=_imax(grp.get("commit") or []),
                    apply=_imax(grp.get("apply") or []),
                    reads=(reads if g == 0 else {}),
                    repair=_repair_state(h),
                    txn=(_txn_state(h) if g == 0 else "-"),
                    topo=(_topo_state(h) if g == 0 else "-"),
                    blame=(_blame_state(h) if g == 0 else "-")))
        elif isinstance(h.get("replicas"), list):   # single-group
            hosts.append(dict(src=src, kind="cluster", age_s=age,
                              loop_error=h.get("loop_error")))
            reps = h["replicas"]
            holders = (h.get("leases") or {}).get("holders") or []
            rows.append(dict(
                src=src, group=0, leader=h.get("leader"),
                lease=(holders[0] if holders else None),
                term=_imax(r.get("term") for r in reps),
                commit=_imax(r.get("commit") for r in reps),
                apply=_imax(r.get("apply") for r in reps),
                reads=_reads_by_path(h),
                repair=_repair_state(h),
                txn=_txn_state(h),
                topo=_topo_state(h),
                blame=_blame_state(h)))
        elif "replica" in h:                        # one member file
            hosts.append(dict(src=src, kind="replica",
                              replica=h.get("replica"), age_s=age))
            members.append((src, h))
        else:
            hosts.append(dict(src=src, kind="unknown"))

    if members:
        # N per-host member snapshots = one cluster seen from N sides
        # (key on term only: two stale files can claim the same term,
        # and tuple-max would fall through to comparing dicts)
        claims = [(int(h.get("term", -1)), h) for _, h in members
                  if h.get("role") == ROLE_LEADER]
        lead = (max(claims, key=lambda c: c[0])[1].get("replica")
                if claims else None)
        rows.append(dict(
            src="+".join(src for src, _ in members), group=0,
            leader=lead, lease=None,
            term=_imax(h.get("term") for _, h in members),
            commit=_imax(h.get("commit") for _, h in members),
            apply=_imax(h.get("apply") for _, h in members),
            reads={}, repair="-", txn="-", topo="-", blame="-",
            members=len(members)))

    # dedupe alerts by name, keeping the longest-firing instance
    best: Dict[str, dict] = {}
    for a in alerts:
        cur = best.get(a["name"])
        if cur is None or (a.get("duration_s") or 0) > (
                cur.get("duration_s") or 0):
            best[a["name"]] = a
    return dict(groups=sorted(rows, key=lambda r: (str(r["src"]),
                                                   r["group"])),
                alerts=sorted(best.values(), key=lambda a: a["name"]),
                hosts=hosts, ts=now)


def _fmt_reads(reads: Dict[str, float],
               prev: Optional[Dict[str, float]] = None,
               dt: Optional[float] = None) -> str:
    if not reads:
        return "-"
    if prev is not None and dt and dt > 0:
        return " ".join(
            f"{k}:{max(0.0, (v - prev.get(k, 0.0))) / dt:.0f}/s"
            for k, v in sorted(reads.items()))
    return " ".join(f"{k}:{v:.0f}" for k, v in sorted(reads.items()))


def render_table(view: dict, prev: Optional[dict] = None) -> str:
    """The operator table. With a previous view (watch mode), read
    counters render as per-second rates over the refresh interval."""
    dt = (view["ts"] - prev["ts"]) if prev else None
    prev_reads = {}
    if prev:
        for r in prev["groups"]:
            prev_reads[(r["src"], r["group"])] = r["reads"]
    hdr = (f"{'GROUP':<6} {'LEADER':<7} {'LEASE':<6} {'TERM':<6} "
           f"{'COMMIT':<10} {'APPLY':<10} {'REPAIR':<14} "
           f"{'TXN':<12} {'TOPO':<12} {'BLAME':<18} READS")
    lines = [hdr, "-" * len(hdr)]
    for r in view["groups"]:
        def cell(v, dash="-"):
            return dash if v is None else str(v)
        lines.append(
            f"{cell(r['group']):<6} {cell(r['leader']):<7} "
            f"{cell(r['lease']):<6} {cell(r['term']):<6} "
            f"{cell(r['commit']):<10} {cell(r['apply']):<10} "
            f"{str(r['repair']):<14} "
            f"{str(r.get('txn', '-')):<12} "
            f"{str(r.get('topo', '-')):<12} "
            f"{str(r.get('blame', '-')):<18} "
            + _fmt_reads(r["reads"],
                         prev_reads.get((r["src"], r["group"])), dt))
    if view["alerts"]:
        lines.append("")
        lines.append("FIRING ALERTS")
        for a in view["alerts"]:
            age = (f"{a['duration_s']:.0f}s"
                   if a.get("duration_s") is not None else "?")
            lines.append(f"  [{a.get('severity', '?'):<4}] "
                         f"{a['name']} (for {age}, "
                         f"value={a.get('value')})")
    lines.append("")
    lines.append("SOURCES")
    for hst in view["hosts"]:
        extra = ""
        if hst.get("loop_error"):
            extra = f"  LOOP ERROR: {hst['loop_error']}"
        elif hst.get("error"):
            extra = f"  UNREACHABLE: {hst['error']}"
        age = (f" age={hst['age_s']}s"
               if hst.get("age_s") is not None else "")
        lines.append(f"  {hst['src']} [{hst['kind']}]{age}{extra}")
    return "\n".join(lines)


def collect(scrapes: List[str], healths: List[str]) -> List[dict]:
    return ([scrape_source(u) for u in scrapes]
            + load_health_files(healths))


# ---------------------------------------------------------------------------
# postmortem bundles
# ---------------------------------------------------------------------------

def _canonical(section) -> bytes:
    return json.dumps(section, sort_keys=True,
                      separators=(",", ":")).encode()


def _sha256(section) -> str:
    return hashlib.sha256(_canonical(section)).hexdigest()


def _read_json(path: str):
    with open(path) as f:
        return json.load(f)


def _series_lines(paths: List[str]) -> List[dict]:
    from rdma_paxos_tpu.obs.series import read_jsonl
    lines: List[dict] = []
    for p in paths:
        lines.extend(read_jsonl(p))
    return lines


def assemble_bundle(*, reason: str = "",
                    workdir: Optional[str] = None,
                    scrape: Optional[str] = None,
                    series: Optional[str] = None,
                    spans: Optional[str] = None,
                    audit: Optional[str] = None,
                    trace: Optional[str] = None,
                    metrics: Optional[str] = None,
                    alerts: Optional[str] = None,
                    health: Optional[List[str]] = None) -> dict:
    """Gather every section from the given inputs (explicit flags win
    over the workdir scan, which wins over the live scrape) and
    return the manifest-stamped bundle document. Missing sections
    stay absent — assembly is best-effort, verification is strict."""
    sections: Dict[str, object] = {}

    if scrape:
        base = scrape.rstrip("/")
        for name, path in (("series", "/series"),
                           ("telemetry", "/metrics.json"),
                           ("health", "/healthz")):
            try:
                sections[name] = _fetch_json(base + path)
            except Exception:       # noqa: BLE001 — best-effort gather
                pass
        try:
            sections["alerts"] = _fetch_json(base + "/alerts")["state"]
        except Exception:           # noqa: BLE001
            pass

    if workdir:
        wd = workdir
        jl = (sorted(_glob.glob(os.path.join(wd, "series.jsonl")))
              + sorted(_glob.glob(os.path.join(
                  wd, "replica*.series.jsonl"))))
        if jl:
            sections["series"] = dict(kind="series_jsonl",
                                      files=[os.path.basename(p)
                                             for p in jl],
                                      lines=_series_lines(jl))
        for name, pats in (
                ("spans", ["spans.json"]),
                ("traces", ["traces.json"]),
                ("audit", ["audit_dump.json", "replica*.audit.json"]),
                ("trace", ["trace_dump.json"]),
                ("telemetry", ["metrics.json"])):
            docs = []
            for pat in pats:
                for p in sorted(_glob.glob(os.path.join(wd, pat))):
                    try:
                        docs.append(_read_json(p))
                    except (OSError, json.JSONDecodeError):
                        continue
            if docs:
                sections[name] = docs[0] if len(docs) == 1 else docs
        hfiles = (sorted(_glob.glob(os.path.join(
            wd, "cluster.health.json")))
            + sorted(_glob.glob(os.path.join(
                wd, "replica*.health.json"))))
        if hfiles:
            hdocs = []
            for p in hfiles:
                try:
                    hdocs.append(_read_json(p))
                except (OSError, json.JSONDecodeError):
                    continue
            if hdocs:
                # workdir beats scrape for EVERY section (the
                # documented precedence) — health included
                sections["health"] = hdocs
        # a cluster health document (or a daemon replica snapshot)
        # carries the alert firing state — the workdir-derived state
        # overrides a scraped one, same precedence as above
        docs = sections.get("health")
        for d in (docs if isinstance(docs, list) else []):
            if isinstance(d, dict) and d.get("alerts"):
                sections["alerts"] = d["alerts"]
                break

    for name, path in (("series", series), ("spans", spans),
                       ("audit", audit), ("trace", trace),
                       ("telemetry", metrics), ("alerts", alerts)):
        if path:
            if name == "series" and path.endswith(".jsonl"):
                sections[name] = dict(kind="series_jsonl",
                                      files=[os.path.basename(path)],
                                      lines=_series_lines([path]))
            else:
                sections[name] = _read_json(path)
    if health:
        sections["health"] = [_read_json(p) for p in health]

    if "spans" in sections:
        # pre-merge the Perfetto timeline (spans + subsystem traces on
        # the shared clock) so the bundle is directly loadable in
        # https://ui.perfetto.dev — an alert exemplar's trace id
        # resolves here without re-running the merge CLI
        try:
            from rdma_paxos_tpu.obs.tracectx import merge_timeline
            sd = sections["spans"]
            td = sections.get("traces", [])
            sections["perfetto"] = merge_timeline(
                sd if isinstance(sd, list) else [sd],
                td if isinstance(td, list) else [td])
        except Exception:           # noqa: BLE001 — best-effort gather
            pass

    manifest = {name: dict(sha256=_sha256(sec),
                           bytes=len(_canonical(sec)))
                for name, sec in sorted(sections.items())}
    return dict(schema=BUNDLE_SCHEMA, kind=BUNDLE_KIND,
                reason=reason, created=time.time(),
                anchor=clock_anchor(),
                sections=sections, manifest=manifest)


def write_bundle(doc: dict, path: str) -> str:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, path)
    return path


def verify_bundle(doc: dict) -> List[str]:
    """-> list of problems (empty = verified): wrong kind, a missing
    or empty core section, a manifest entry whose digest no longer
    matches its section (tamper/corruption), or an unmanifested
    section."""
    problems = []
    if doc.get("kind") != BUNDLE_KIND:
        return [f"not a postmortem bundle (kind={doc.get('kind')!r})"]
    sections = doc.get("sections") or {}
    manifest = doc.get("manifest") or {}
    for name in REQUIRED_SECTIONS:
        if name not in sections or sections[name] in (None, [], {}):
            problems.append(f"missing core section: {name}")
    for name, sec in sections.items():
        ent = manifest.get(name)
        if ent is None:
            problems.append(f"section {name} not in manifest")
        elif ent.get("sha256") != _sha256(sec):
            problems.append(f"section {name} digest mismatch "
                            "(tampered or corrupted)")
    for name in manifest:
        if name not in sections:
            problems.append(f"manifest names absent section {name}")
    return problems


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _watch(args) -> int:
    prev = None
    while True:
        view = fleet_view(collect(args.scrape, args.health))
        if args.json:
            print(json.dumps(view, indent=2))
        else:
            if not args.once:
                print("\x1b[2J\x1b[H", end="")   # clear + home
            stamp = time.strftime("%H:%M:%S")
            print(f"rdma_paxos_tpu fleet console  {stamp}  "
                  f"({len(view['hosts'])} source(s))")
            print(render_table(view, prev))
        if args.once:
            # CI contract: exit 1 when any source is dead or any page
            # fires, so a scripted check can gate on the console
            dead = any(h.get("kind") == "error"
                       or h.get("loop_error")
                       for h in view["hosts"])
            paged = any(a.get("severity") == "page"
                        for a in view["alerts"])
            return 1 if (dead or paged) and args.strict else 0
        prev = view
        time.sleep(args.interval)


def _bundle(args) -> int:
    if args.verify:
        try:
            doc = _read_json(args.verify)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"bundle unreadable: {exc}")
            return 1
        problems = verify_bundle(doc)
        sections = sorted((doc.get("sections") or {}))
        if problems:
            print(f"bundle INVALID ({args.verify}):")
            for p in problems:
                print(f"  - {p}")
            return 1
        print(f"bundle OK ({args.verify}): sections="
              f"{','.join(sections)} reason={doc.get('reason')!r}")
        return 0
    if not args.out:
        print("bundle needs --out FILE (or --verify FILE)")
        return 2
    doc = assemble_bundle(
        reason=args.reason, workdir=args.workdir, scrape=args.scrape,
        series=args.series, spans=args.spans, audit=args.audit,
        trace=args.trace, metrics=args.metrics, alerts=args.alerts,
        health=args.health or None)
    write_bundle(doc, args.out)
    missing = [n for n in REQUIRED_SECTIONS
               if n not in doc["sections"]]
    print(f"bundle written: {args.out} "
          f"(sections={','.join(sorted(doc['sections']))})")
    if missing:
        print(f"  warning: core sections missing: "
              f"{','.join(missing)} (bundle --verify will fail)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "bundle":
        ap = argparse.ArgumentParser(
            prog="rdma_paxos_tpu.obs.console bundle",
            description="assemble / verify a postmortem bundle")
        ap.add_argument("--out", default=None,
                        help="write the assembled bundle here")
        ap.add_argument("--verify", default=None, metavar="FILE",
                        help="verify an existing bundle (exit 0 iff "
                             "untampered + all core sections present)")
        ap.add_argument("--workdir", default=None,
                        help="scan a driver/daemon workdir for the "
                             "conventional dump files")
        ap.add_argument("--scrape", default=None,
                        help="pull series/telemetry/alerts/health "
                             "from a live ops exporter URL")
        ap.add_argument("--reason", default="operator request")
        ap.add_argument("--series", default=None,
                        help="series JSONL (or JSON) file")
        ap.add_argument("--spans", default=None,
                        help="span dump JSON file")
        ap.add_argument("--audit", default=None,
                        help="audit artifact / ledger dump JSON file")
        ap.add_argument("--trace", default=None,
                        help="trace-ring dump JSON file")
        ap.add_argument("--metrics", default=None,
                        help="registry snapshot JSON file "
                             "(the telemetry section)")
        ap.add_argument("--alerts", default=None,
                        help="alert-state JSON file")
        ap.add_argument("--health", action="append", default=[],
                        help="health snapshot JSON file (repeatable)")
        return _bundle(ap.parse_args(argv[1:]))

    ap = argparse.ArgumentParser(
        prog="rdma_paxos_tpu.obs.console",
        description="live fleet view merged from health files and/or "
                    "scraped ops endpoints")
    ap.add_argument("--scrape", action="append", default=[],
                    metavar="URL",
                    help="ops exporter base URL (repeatable)")
    ap.add_argument("--health", action="append", default=[],
                    metavar="PATH_OR_GLOB",
                    help="health snapshot file(s) (repeatable, glob "
                         "ok)")
    ap.add_argument("--once", action="store_true",
                    help="render one table and exit (CI mode)")
    ap.add_argument("--strict", action="store_true",
                    help="with --once: exit 1 when a source is dead "
                         "or a page-severity alert is firing")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="watch refresh period (seconds)")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged view as JSON")
    args = ap.parse_args(argv)
    if not args.scrape and not args.health:
        ap.error("need at least one --scrape URL or --health PATH")
    try:
        return _watch(args)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
