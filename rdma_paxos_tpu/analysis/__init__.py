"""graftlint — repo-native static analysis for the invariants the
replication engine's correctness rests on.

Five passes over the source tree (``python -m rdma_paxos_tpu.analysis``):

``jit-purity``       no host-side symbol (obs, threading, wall clock,
                     unseeded randomness) reachable from the device
                     modules that run inside jit/shard_map — and the
                     declared host-pure modules never reach into jax.
``cache-key``        every builder that stores a compiled program into
                     STEP_CACHE folds each static flag it reads into
                     the cache key (the "new flag, forgotten key
                     component" bug class, closed for all builders).
``lock-discipline``  every access to a ``# guarded-by:``-annotated
                     field happens under the declared lock (or is a
                     justified baseline entry).
``determinism``      no wall clock / unseeded randomness in the chaos,
                     replay, and step-domain modules (obs/clock.py is
                     the single wall anchor).
``thread-hygiene``   every spawned thread has a stop/join path; HTTP
                     serving handlers answer errors instead of dying.

Findings are ``Finding(file, line, pass_id, message)``; justified
exceptions live in ``analysis/baseline.toml`` (one ``[[suppress]]``
block each, with a reason). The companion runtime sanitizer
(``analysis/runtime_guard.py``, enabled by ``RP_SANITIZE=1``) turns
the same ``guarded-by`` declarations into per-access lock-ownership
assertions at run time.
"""

from rdma_paxos_tpu.analysis.engine import (  # noqa: F401
    Finding, PASS_IDS, default_baseline_path, load_baseline,
    repo_root, run_analysis)
from rdma_paxos_tpu.analysis.purity import (  # noqa: F401
    DEVICE_MODULES, HOST_PURE_MODULES, SCAN_PATTERNS)


def jit_purity_findings(root=None):
    """Run ONLY the jit-purity pass (baseline applied) — the single
    source of truth behind the ``test_jit_safety_scan_*`` tier-1
    wrappers."""
    report = run_analysis(root=root, passes=("jit-purity",))
    return report.findings


def assert_jit_purity(root=None) -> None:
    """Assert-style wrapper for the tier-1 jit-safety tests: raises
    AssertionError naming every finding if the device/host purity
    contract is violated anywhere."""
    findings = jit_purity_findings(root)
    assert not findings, "jit-purity violations:\n" + "\n".join(
        str(f) for f in findings)
