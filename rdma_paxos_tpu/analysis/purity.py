"""jit-purity pass: the device modules stay pure, the host-pure
modules stay off the accelerator.

The device-module manifest lives HERE, in one place: a PR that adds a
new compiled surface extends ``DEVICE_MODULES`` once and every rule —
transitive import provenance, stdlib bans, and the source-pattern scan
— covers it automatically. This pass is the single source of truth
behind the six tier-1 ``test_jit_safety_scan_*`` wrappers that used to
carry six diverging copies of the regex list.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from rdma_paxos_tpu.analysis.engine import Finding, SourceTree

PASS_ID = "jit-purity"

# modules whose code runs INSIDE jit/shard_map (compiled surfaces).
# Everything transitively imported from here lands in a trace.
DEVICE_MODULES = (
    "rdma_paxos_tpu/consensus/step.py",
    "rdma_paxos_tpu/ops/__init__.py",
    "rdma_paxos_tpu/ops/quorum.py",
    "rdma_paxos_tpu/parallel/mesh.py",
    "rdma_paxos_tpu/txn/lane.py",
)

# no module reachable from a device module may come from these: host
# orchestration, observability, threads, wall clock, global-state
# randomness (jax.random is fine — it is seeded and traced).
FORBIDDEN_DEVICE_IMPORTS = (
    "rdma_paxos_tpu.obs",
    "rdma_paxos_tpu.runtime",
    "rdma_paxos_tpu.chaos",
    "rdma_paxos_tpu.shard",
    "rdma_paxos_tpu.proxy",
    "rdma_paxos_tpu.models",
    "rdma_paxos_tpu.analysis",
    "threading",
    "time",
    "random",
    "socket",
    "subprocess",
    "http",
)

# source-pattern scan over the device modules (comments included —
# an obs call site hiding in dead code is one uncomment away from a
# cache-key change). The union of the six scattered test lists, deduped.
SCAN_PATTERNS: Tuple[str, ...] = (
    r"rdma_paxos_tpu\.obs",
    # the catch-all from the old test_spans copy: ANY obs.* reference
    # in a device module is a leak, named submodule or not
    r"\bobs\.",
    r"\.metrics\.(inc|set|observe)\b",
    r"\.trace\.record\b",
    r"\.spans\.\w+\(",
    r"\bAuditLedger\b",
    r"\bFlightRecorder\b",
    r"\bAlertEngine\b",
    r"\bProfilerSession\b",
    r"jax\.profiler",
    r"\bMetricsRegistry\b",
    r"runtime\.reads",
    r"runtime\.repair",
    r"\bLeaseManager\b",
    r"\bReadHub\b",
    r"\breads_served\b",
    r"\bserving_holder\b",
    r"\bRepairController\b",
    r"\binstall_snapshot\b",
    r"\btake_snapshot\b",
    r"\bTimeSeriesStore\b",
    r"\bOpsExporter\b",
    r"\brender_prometheus\b",
    r"\bserve_metrics\b",
    r"\bfleet_view\b",
    r"\bassemble_bundle\b",
    r"\bthreading\b",
)

# host-pure modules: pure host orchestration/data-plane code that must
# never reach back into the accelerator stack. Each entry: banned
# import roots (AST-level) + banned source patterns. hostpath.py (the
# PR 13 vectorized data plane, previously uncovered by any scan test)
# bans by IMPORT only — its docstring legitimately names jax to forbid
# it.
HOST_PURE_MODULES: Dict[str, dict] = {
    "rdma_paxos_tpu/runtime/hostpath.py": dict(
        ban_imports=("jax", "jaxlib"),
        patterns=(r"\bjnp\b", r"shard_map")),
    "rdma_paxos_tpu/runtime/reads.py": dict(
        ban_imports=("jax", "jaxlib"),
        patterns=(r"\bjax\b", r"\bjnp\b", r"shard_map")),
    # the adaptive dispatch governor: pure host control-plane logic —
    # it picks WHICH prewarmed program runs, and must never be able
    # to build one
    "rdma_paxos_tpu/runtime/governor.py": dict(
        ban_imports=("jax", "jaxlib", "numpy"),
        patterns=(r"\bjnp\b", r"shard_map", r"\bbuild_")),
    "rdma_paxos_tpu/runtime/repair.py": dict(
        ban_imports=(),
        patterns=(r"jax\.jit", r"shard_map")),
    "rdma_paxos_tpu/obs/series.py": dict(
        ban_imports=("jax", "jaxlib"),
        patterns=(r"\bjax\b", r"\bjnp\b", r"shard_map")),
    "rdma_paxos_tpu/obs/export.py": dict(
        ban_imports=("jax", "jaxlib"),
        patterns=(r"\bjax\b", r"\bjnp\b", r"shard_map")),
    # the unified trace plane: cross-subsystem provenance + blame is
    # pure host bookkeeping — it must never touch the device (step
    # programs and cache keys are bit-identical with tracing on)
    "rdma_paxos_tpu/obs/tracectx.py": dict(
        ban_imports=("jax", "jaxlib"),
        patterns=(r"\bjax\b", r"\bjnp\b", r"shard_map")),
    "rdma_paxos_tpu/obs/console.py": dict(
        ban_imports=("jax", "jaxlib"),
        patterns=(r"\bjax\b", r"\bjnp\b", r"shard_map")),
    # log-as-product streams: scan/watch/CDC are pure host tail
    # followers over already-decoded replay batches — pinned like
    # reads.py so they can never grow a device dependency
    "rdma_paxos_tpu/streams/__init__.py": dict(
        ban_imports=("jax", "jaxlib"),
        patterns=(r"\bjax\b", r"\bjnp\b", r"shard_map")),
    "rdma_paxos_tpu/streams/tail.py": dict(
        ban_imports=("jax", "jaxlib"),
        patterns=(r"\bjax\b", r"\bjnp\b", r"shard_map")),
    "rdma_paxos_tpu/streams/scan.py": dict(
        ban_imports=("jax", "jaxlib"),
        patterns=(r"\bjax\b", r"\bjnp\b", r"shard_map")),
    "rdma_paxos_tpu/streams/watch.py": dict(
        ban_imports=("jax", "jaxlib"),
        patterns=(r"\bjax\b", r"\bjnp\b", r"shard_map")),
    "rdma_paxos_tpu/streams/cdc.py": dict(
        ban_imports=("jax", "jaxlib", "numpy"),
        patterns=(r"\bjax\b", r"\bjnp\b", r"shard_map")),
    # elastic topology: the shared epoch/completion-proof helpers and
    # the load policy are pure host control plane — splits reshape
    # host routing only, so neither may ever grow a device dependency
    # (zero new STEP_CACHE keys is pinned by test on top of this).
    # Import-level bans only for the jax root: both docstrings
    # legitimately NAME jax to forbid it.
    "rdma_paxos_tpu/topology/epoch.py": dict(
        ban_imports=("jax", "jaxlib"),
        patterns=(r"\bjnp\b", r"shard_map")),
    "rdma_paxos_tpu/topology/policy.py": dict(
        ban_imports=("jax", "jaxlib", "numpy"),
        patterns=(r"\bjnp\b", r"shard_map", r"\bbuild_")),
}


def _forbidden(dotted: str) -> Optional[str]:
    for p in FORBIDDEN_DEVICE_IMPORTS:
        if dotted == p or dotted.startswith(p + "."):
            return p
    return None


def _imports_of(mod) -> List[Tuple[str, int]]:
    """(dotted target, line) for every import statement in the module,
    function-level imports included; ``from p import name`` edges
    cover both ``p`` and — when it resolves to a file — ``p.name``."""
    out: List[Tuple[str, int]] = []
    pkg_parts = mod.dotted.split(".")
    # a module's package: drop the leaf (``__init__`` already dropped)
    pkg = pkg_parts[:-1]
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out.append((a.name, node.lineno))
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg[: len(pkg) - (node.level - 1)]
                target = ".".join(base + ([node.module]
                                          if node.module else []))
            else:
                target = node.module or ""
            if target:
                out.append((target, node.lineno))
                for a in node.names:
                    out.append((target + "." + a.name, node.lineno))
    return out


def _closure_findings(tree: SourceTree, root_rel: str) -> List[Finding]:
    """BFS the package-internal import graph from one device module;
    flag any reachable forbidden module, naming the import chain."""
    findings: List[Finding] = []
    root_mod = tree.module(root_rel)
    # rel -> (parent rel, import line in parent) for chain rendering
    seen: Dict[str, Optional[Tuple[str, int]]] = {root_rel: None}
    reported = set()       # (module, line, forbidden root) dedupe
    queue = [root_rel]
    while queue:
        rel = queue.pop(0)
        mod = tree.module(rel)
        for dotted, line in _imports_of(mod):
            bad = _forbidden(dotted)
            if bad is not None:
                if (rel, line, bad) in reported:
                    continue
                reported.add((rel, line, bad))
                # report at the DEVICE module (the actionable site):
                # for transitive hits, the chain names the path
                chain = [rel]
                cur = rel
                while seen.get(cur) is not None:
                    cur = seen[cur][0]
                    chain.append(cur)
                chain.reverse()
                first_line = (line if rel == root_rel
                              else _root_import_line(
                                  root_mod, tree, chain[1]))
                findings.append(Finding(
                    file=root_rel, line=first_line, pass_id=PASS_ID,
                    message="forbidden host-side module %r (matches "
                            "%r) reachable from device module via %s "
                            "(%s:%d)" % (dotted, bad,
                                         " -> ".join(chain), rel,
                                         line)))
                continue
            sub = tree.rel_of_dotted(dotted)
            if sub is not None and sub not in seen:
                seen[sub] = (rel, line)
                queue.append(sub)
    return findings


def _root_import_line(root_mod, tree: SourceTree, second_rel: str) -> int:
    """The line in the device module importing the first chain hop."""
    for dotted, line in _imports_of(root_mod):
        if tree.rel_of_dotted(dotted) == second_rel:
            return line
    return 1


def _pattern_findings(tree: SourceTree, rel: str,
                      patterns) -> List[Finding]:
    mod = tree.module(rel)
    out: List[Finding] = []
    for pat in patterns:
        rx = re.compile(pat)
        for i, line in enumerate(mod.lines, 1):
            if rx.search(line):
                out.append(Finding(
                    file=rel, line=i, pass_id=PASS_ID,
                    message="forbidden source pattern %r: %r" %
                            (pat, line.strip()[:80])))
                break     # one finding per pattern per file is enough
    return out


def _host_pure_findings(tree: SourceTree, rel: str,
                        spec: dict) -> List[Finding]:
    mod = tree.module(rel)
    out: List[Finding] = []
    roots = spec.get("ban_imports", ())
    if roots:
        for dotted, line in _imports_of(mod):
            head = dotted.split(".")[0]
            if head in roots:
                out.append(Finding(
                    file=rel, line=line, pass_id=PASS_ID,
                    message="host-pure module imports accelerator "
                            "module %r" % dotted))
    for pat in spec.get("patterns", ()):
        rx = re.compile(pat)
        for i, line in enumerate(mod.lines, 1):
            if rx.search(line):
                out.append(Finding(
                    file=rel, line=i, pass_id=PASS_ID,
                    message="host-pure module matches accelerator "
                            "pattern %r: %r" % (pat,
                                                line.strip()[:80])))
                break
    return out


def run(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []
    for rel in DEVICE_MODULES:
        if not tree.has(rel):
            continue          # partial fixture trees
        findings.extend(_closure_findings(tree, rel))
        findings.extend(_pattern_findings(tree, rel, SCAN_PATTERNS))
    for rel, spec in HOST_PURE_MODULES.items():
        if tree.has(rel):
            findings.extend(_host_pure_findings(tree, rel, spec))
    return findings
