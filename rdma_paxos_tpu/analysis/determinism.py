"""determinism pass: no wall clock / unseeded randomness in the
chaos, replay, and step-domain modules.

Chaos verdicts, recorded workloads, and step-domain protocol logic
must replay bit-identically from a seed; a single ``time.time()`` or
global-state ``random.random()`` in those paths silently turns a
reproducer artifact into a flake. ``obs/clock.py`` is the one
sanctioned wall anchor — everything in scope here must either be
step-domain or draw randomness from an explicitly seeded generator
(``random.Random(seed)``, ``np.random.default_rng(seed)``,
``jax.random`` keys).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence

from rdma_paxos_tpu.analysis.engine import (
    Finding, SourceTree, import_aliases)

PASS_ID = "determinism"

# the replay-deterministic scope: chaos + step-domain protocol/engine
# code. Drivers/daemons/obs are wall-clock domain by design (poll
# cadences, timeouts, exporters) and are NOT in scope.
SCOPE = (
    "rdma_paxos_tpu/chaos/",
    "rdma_paxos_tpu/consensus/",
    "rdma_paxos_tpu/ops/",
    "rdma_paxos_tpu/parallel/",
    "rdma_paxos_tpu/shard/",
    "rdma_paxos_tpu/runtime/sim.py",
    "rdma_paxos_tpu/runtime/timers.py",
    "rdma_paxos_tpu/runtime/hostpath.py",
    # governor decisions must be pure step-domain functions of the
    # observed inputs (chaos verdicts with a governor attached stay
    # bit-reproducible) — no wall clock, no unseeded randomness
    "rdma_paxos_tpu/runtime/governor.py",
)

# attribute references (calls or not — a ``clock=time.monotonic``
# default argument smuggles the wall clock in just as surely)
BANNED_TIME = {"time", "monotonic", "perf_counter", "perf_counter_ns",
               "monotonic_ns", "time_ns", "sleep", "clock"}
BANNED_DATETIME = {"now", "utcnow", "today"}
# global-state randomness; seeded constructors stay legal
ALLOWED_RANDOM = {"Random", "SystemRandom"}
ALLOWED_NP_RANDOM = {"default_rng", "Generator", "SeedSequence",
                     "BitGenerator", "PCG64", "Philox"}


def in_scope(rel: str, scope: Sequence[str] = SCOPE) -> bool:
    return any(rel == s or (s.endswith("/") and rel.startswith(s))
               for s in scope)


def _module_of(aliases: Dict[str, str], node: ast.AST) -> Optional[str]:
    """For ``X.attr`` where X is a Name bound by ``import m as X``,
    the dotted module m; else None."""
    if isinstance(node, ast.Name):
        return aliases.get(node.id)
    return None


def run(tree: SourceTree,
        scope: Sequence[str] = SCOPE) -> List[Finding]:
    findings: List[Finding] = []
    for rel in tree.files():
        if not in_scope(rel, scope):
            continue
        mod = tree.module(rel)
        aliases = import_aliases(mod.tree)
        # from-imports smuggle the same seams as attribute access:
        # ``from time import perf_counter`` is a bare Name at the call
        # site, invisible to the attribute walk below — flag the
        # import itself (the import IS the wall-clock dependency)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ImportFrom) or node.level:
                continue
            if node.module == "time":
                for a in node.names:
                    if a.name in BANNED_TIME:
                        findings.append(Finding(
                            file=rel, line=node.lineno,
                            pass_id=PASS_ID,
                            message="wall clock from-import time.%s "
                                    "in a replay-deterministic module"
                                    % a.name))
            elif node.module == "random":
                for a in node.names:
                    if a.name not in ALLOWED_RANDOM:
                        findings.append(Finding(
                            file=rel, line=node.lineno,
                            pass_id=PASS_ID,
                            message="global-state randomness "
                                    "from-import random.%s — use a "
                                    "seeded random.Random(...)"
                                    % a.name))
            elif node.module == "datetime":
                for a in node.names:
                    if a.name in ("datetime", "date"):
                        findings.append(Finding(
                            file=rel, line=node.lineno,
                            pass_id=PASS_ID,
                            message="wall clock from-import "
                                    "datetime.%s in a replay-"
                                    "deterministic module (its "
                                    ".now()/.today() are wall "
                                    "anchors)" % a.name))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Attribute):
                continue
            base = _module_of(aliases, node.value)
            if base is None:
                # datetime.datetime.now: Attribute over Attribute
                if (isinstance(node.value, ast.Attribute)
                        and node.value.attr == "datetime"
                        and _module_of(aliases,
                                       node.value.value) == "datetime"
                        and node.attr in BANNED_DATETIME):
                    findings.append(Finding(
                        file=rel, line=node.lineno, pass_id=PASS_ID,
                        message="wall clock datetime.datetime.%s in a "
                                "replay-deterministic module" %
                                node.attr))
                continue
            if base == "time" and node.attr in BANNED_TIME:
                findings.append(Finding(
                    file=rel, line=node.lineno, pass_id=PASS_ID,
                    message="wall clock time.%s in a replay-"
                            "deterministic module (obs/clock.py is "
                            "the single wall anchor)" % node.attr))
            elif base == "random" and node.attr not in ALLOWED_RANDOM:
                findings.append(Finding(
                    file=rel, line=node.lineno, pass_id=PASS_ID,
                    message="global-state randomness random.%s — use "
                            "a seeded random.Random(...)" % node.attr))
            elif (base == "numpy" and isinstance(node.value,
                                                 ast.Attribute)):
                pass    # handled below via numpy.random chain
        # numpy.random.X chains: np.random.<fn> with np aliasing numpy
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Attribute)
                    and node.value.attr == "random"
                    and _module_of(aliases,
                                   node.value.value) == "numpy"
                    and node.attr not in ALLOWED_NP_RANDOM):
                findings.append(Finding(
                    file=rel, line=node.lineno, pass_id=PASS_ID,
                    message="global-state randomness np.random.%s — "
                            "use np.random.default_rng(seed)" %
                            node.attr))
    return findings
