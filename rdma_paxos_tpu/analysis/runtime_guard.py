"""Runtime lock sanitizer — ThreadSanitizer-lite for the host state.

Under ``RP_SANITIZE=1`` the engines and drivers wrap themselves in a
dynamic subclass whose ``__setattr__`` (and, for ``[strict]`` fields,
``__getattribute__``) asserts that the lock declared by the field's
``# guarded-by:`` annotation is HELD BY THE ACCESSING THREAD. A
latent readback/dispatch race — the single largest post-review-rider
class in this repo — then fails the offending test at the exact
access instead of corrupting a queue one run in a thousand.

Semantics, derived from the same registry the static
``lock-discipline`` pass reads (``analysis/locks.py``):

- every guarded field: attribute WRITES assert lock ownership;
- ``[strict]`` fields: attribute READS assert too (the declaration
  promises no lock-free read exists);
- ``[writes]``/default fields: reads stay unchecked at runtime —
  the static pass plus ``baseline.toml`` govern those.

``threading.RLock`` carries ownership natively (``_is_owned``);
declared plain ``threading.Lock`` locks are transparently replaced at
guard time with an ownership-tracking wrapper (installation happens in
``__init__``, before the object is shared, so no other reference to
the bare lock can exist yet).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, Optional

SANITIZE_ENV = "RP_SANITIZE"


def sanitize_enabled() -> bool:
    return os.environ.get(SANITIZE_ENV, "") not in ("", "0")


class LockDisciplineError(AssertionError):
    """A guarded field was accessed without its declared lock held."""


class OwnedLock:
    """``threading.Lock`` with ownership tracking — drop-in for the
    ``with``/acquire/release surface the runtime uses."""

    def __init__(self, inner=None):
        self._inner = inner or threading.Lock()
        self._owner: Optional[int] = None

    def acquire(self, *a, **kw) -> bool:
        got = self._inner.acquire(*a, **kw)
        if got:
            self._owner = threading.get_ident()
        return got

    def release(self) -> None:
        self._owner = None
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()


def _owned(lock) -> bool:
    probe = getattr(lock, "_is_owned", None)
    if probe is not None:
        return bool(probe())
    # last resort (foreign lock type): held-by-anyone
    return bool(lock.locked())


_SUBCLASS_CACHE: Dict[tuple, type] = {}


def _registry_for(files: Iterable[str], lock_attr: str):
    """(write-checked fields, read-checked fields) declared under
    ``lock_attr`` in the given source files — the static pass's
    ``guarded-by`` grammar, reused verbatim."""
    from rdma_paxos_tpu.analysis.locks import parse_registry_text
    writes, reads = set(), set()
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        for gf in parse_registry_text(text, path):
            if gf.lock != lock_attr:
                continue
            writes.add(gf.attr)
            if gf.mode == "strict":
                reads.add(gf.attr)
    return frozenset(writes), frozenset(reads)


def guard(obj, lock_attr: str, write_fields: Iterable[str],
          read_fields: Iterable[str] = ()):
    """Swap ``obj``'s class for a checking subclass. Idempotent per
    (class, lock, field-set). Returns ``obj``."""
    cls = type(obj)
    if getattr(cls, "__rp_sanitized__", False):
        return obj
    wf, rf = frozenset(write_fields), frozenset(read_fields)
    if not wf and not rf:
        return obj
    lock = getattr(obj, lock_attr)
    if isinstance(lock, type(threading.Lock())):
        # ownership-tracking replacement; see module docstring for
        # why this is safe at construction time
        object.__setattr__(obj, lock_attr, OwnedLock(lock))
    key = (cls, lock_attr, wf, rf)
    sub = _SUBCLASS_CACHE.get(key)
    if sub is None:

        def _check(self, name: str, verb: str) -> None:
            lk = object.__getattribute__(self, lock_attr)
            if not _owned(lk):
                raise LockDisciplineError(
                    "RP_SANITIZE: %s of %s.%s on thread %r without "
                    "%s held (declared guarded-by %s)" %
                    (verb, cls.__name__, name,
                     threading.current_thread().name, lock_attr,
                     lock_attr))

        class _Sanitized(cls):    # type: ignore[misc, valid-type]
            __rp_sanitized__ = True

            def __setattr__(self, name, value):
                if name in wf:
                    _check(self, name, "write")
                object.__setattr__(self, name, value)

            def __getattribute__(self, name):
                if name in rf:
                    _check(self, name, "read")
                return object.__getattribute__(self, name)

        _Sanitized.__name__ = cls.__name__ + "+sanitized"
        _Sanitized.__qualname__ = _Sanitized.__name__
        sub = _SUBCLASS_CACHE[key] = _Sanitized
    obj.__class__ = sub
    return obj


def maybe_guard(obj, lock_attr: str, *source_files: str):
    """The engines'/drivers' one-line wiring: a no-op unless
    ``RP_SANITIZE=1``; otherwise derive the field sets from the
    ``guarded-by`` annotations in ``source_files`` (usually the
    caller's ``__file__``) and install the proxy."""
    if not sanitize_enabled():
        return obj
    writes, reads = _registry_for(source_files, lock_attr)
    return guard(obj, lock_attr, writes, reads)
