"""lock-discipline pass: every access to a ``# guarded-by:``-annotated
field happens under its declared lock.

This is the single largest post-review-rider bug class in this repo's
history (ticket-retirement/submit()/_drive_config_change races, the
ReadHub ticket race, the tied-term merge crash): host state shared
between the dispatch thread, the readback thread, and app/client
threads, mutated one forgotten lock away from a race. The fields now
DECLARE their lock in the source, and this pass (plus the
``RP_SANITIZE=1`` runtime proxy in ``runtime_guard.py``) enforces it.

Annotation grammar, on (or directly above) the field's ``__init__``
assignment::

    self.pending = ...          # guarded-by: _host_lock
    self.last = None            # guarded-by: _host_lock [writes]
    self._submitq = ...         # guarded-by: _lock [strict]

- default: reads AND writes must hold the lock statically; the
  runtime sanitizer asserts writes.
- ``[writes]``: only writes are checked (lock-free reads are part of
  the field's published contract — e.g. pointer-swap publication of
  an immutable snapshot).
- ``[strict]``: like the default, and the runtime sanitizer asserts
  READS too (no lock-free read of this field exists anywhere).

Function-level exemptions:

- ``__init__`` bodies (construction precedes sharing);
- functions whose name ends in ``_locked`` (the repo's existing
  caller-holds-the-lock naming contract);
- functions carrying ``# holds-lock: <lockname>`` on or above the
  ``def`` line (documented caller-holds contract without the suffix).

Anything else is a finding; intentional lock-free accesses that are
genuinely safe get a one-line justification in ``baseline.toml``.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from rdma_paxos_tpu.analysis.engine import (
    Finding, SourceTree, attr_chain)

PASS_ID = "lock-discipline"

# the threaded runtime modules: where guarded fields are declared AND
# where accesses are checked (attr-name matching also catches e.g.
# ``self.cluster.pending`` reads from the drivers)
LOCK_MODULES = (
    "rdma_paxos_tpu/runtime/sim.py",
    "rdma_paxos_tpu/runtime/driver.py",
    "rdma_paxos_tpu/runtime/sharded_driver.py",
    "rdma_paxos_tpu/runtime/repair.py",
    "rdma_paxos_tpu/runtime/reads.py",
    "rdma_paxos_tpu/runtime/governor.py",
    "rdma_paxos_tpu/shard/cluster.py",
    "rdma_paxos_tpu/streams/__init__.py",
    "rdma_paxos_tpu/streams/scan.py",
    "rdma_paxos_tpu/streams/watch.py",
    "rdma_paxos_tpu/topology/transition.py",
    "rdma_paxos_tpu/topology/policy.py",
    "rdma_paxos_tpu/obs/tracectx.py",
)

_GUARD_RE = re.compile(
    r"#\s*guarded-by:\s*([A-Za-z_]\w*)(?:\s*\[(\w+)\])?")
_FIELD_RE = re.compile(r"self\.([A-Za-z_]\w*)\s*[:=]")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*([A-Za-z_]\w*)")

MODES = ("full", "writes", "strict")


@dataclass(frozen=True)
class GuardedField:
    attr: str
    lock: str          # lock attribute name, e.g. "_host_lock"
    mode: str          # "full" | "writes" | "strict"
    file: str
    line: int


def parse_registry_text(text: str, rel: str) -> List[GuardedField]:
    """Extract guarded-field declarations from one module's source.
    The annotated field is the ``self.X = / self.X:`` on the comment's
    own line, else the first such assignment within the next 3 lines
    (annotation-above style)."""
    out: List[GuardedField] = []
    lines = text.splitlines()
    for i, line in enumerate(lines):
        m = _GUARD_RE.search(line)
        if m is None:
            continue
        lock, mode = m.group(1), (m.group(2) or "full")
        for j in range(i, min(i + 4, len(lines))):
            fm = _FIELD_RE.search(lines[j])
            if fm:
                out.append(GuardedField(
                    attr=fm.group(1), lock=lock, mode=mode,
                    file=rel, line=j + 1))
                break
    return out


def build_registry(tree: SourceTree,
                   modules: Sequence[str] = LOCK_MODULES
                   ) -> (Dict[str, GuardedField], List[Finding]):
    """attr -> declaration, plus findings for malformed/conflicting
    declarations (same attr declared under different locks across the
    threaded modules would make name-based checking ambiguous)."""
    reg: Dict[str, GuardedField] = {}
    findings: List[Finding] = []
    for rel in modules:
        if not tree.has(rel):
            continue
        mod = tree.module(rel)
        for gf in parse_registry_text(mod.text, rel):
            if gf.mode not in MODES:
                findings.append(Finding(
                    file=rel, line=gf.line, pass_id=PASS_ID,
                    message="unknown guarded-by mode %r for %r "
                            "(expected one of %s)" %
                            (gf.mode, gf.attr, list(MODES))))
                continue
            prev = reg.get(gf.attr)
            if prev is not None and (prev.lock != gf.lock
                                     or prev.mode != gf.mode):
                findings.append(Finding(
                    file=rel, line=gf.line, pass_id=PASS_ID,
                    message="field %r re-declared as guarded-by %s "
                            "[%s], conflicting with %s:%d (%s [%s])" %
                            (gf.attr, gf.lock, gf.mode, prev.file,
                             prev.line, prev.lock, prev.mode)))
                continue
            reg.setdefault(gf.attr, gf)
    return reg, findings


def _holds_locks(mod, func) -> set:
    """Lock names a function declares it is called under: the
    ``_locked`` suffix (all locks) or ``# holds-lock:`` comments on or
    directly above the def line."""
    if func.name.endswith("_locked"):
        return {"*"}
    locks = set()
    for ln in range(max(0, func.lineno - 2), func.lineno):
        m = _HOLDS_RE.search(mod.lines[ln])
        if m:
            locks.add(m.group(1))
    # decorator lines can push the def down; also scan the def line(s)
    m = _HOLDS_RE.search(mod.lines[func.lineno - 1])
    if m:
        locks.add(m.group(1))
    return locks


def _with_held(mod, node: ast.AST, lock: str, func) -> bool:
    """Is ``node`` lexically inside a ``with`` whose items include an
    expression ending in ``.{lock}`` (any receiver), within ``func``?"""
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                chain = attr_chain(item.context_expr)
                if chain and chain.split(".")[-1] == lock:
                    return True
        if anc is func:
            break
    return False


def run(tree: SourceTree,
        modules: Sequence[str] = LOCK_MODULES) -> List[Finding]:
    reg, findings = build_registry(tree, modules)
    if not reg:
        return findings
    for rel in modules:
        if not tree.has(rel):
            continue
        mod = tree.module(rel)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Attribute):
                continue
            gf = reg.get(node.attr)
            if gf is None:
                continue
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            if gf.mode == "writes" and not is_write:
                continue
            func = mod.enclosing_function(node)
            if func is None:
                continue          # module-level: import-time, single
            if func.name == "__init__":
                continue          # construction precedes sharing
            held = _holds_locks(mod, func)
            if "*" in held or gf.lock in held:
                continue
            if _with_held(mod, node, gf.lock, func):
                continue
            findings.append(Finding(
                file=rel, line=node.lineno, pass_id=PASS_ID,
                message="%s of %r (guarded-by %s, declared %s:%d) "
                        "outside a `with ...%s` block in %s()" %
                        ("write" if is_write else "read", node.attr,
                         gf.lock, gf.file, gf.line, gf.lock,
                         func.name)))
    return findings
