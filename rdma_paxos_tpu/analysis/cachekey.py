"""cache-key pass: every compiled-program builder folds every static
flag it reads into its STEP_CACHE key.

The bug class this closes: a PR threads a new static build flag (like
``audit=`` or ``telemetry=``) into a builder's ``build_*`` call but
forgets to add it to the cache key — two clusters with different flag
values then silently share one compiled program. The per-geometry
cache-key-guard tests pin one flag combination each; this pass checks
the KEY EXPRESSION itself against the reads, for every builder at
once.

Rule, per ``STEP_CACHE[key] = ...`` (or ``self._STEP_CACHE[...]``)
store site:

- the "miss scope" is the smallest enclosing ``if`` statement (the
  cache-miss guard) or, failing that, the enclosing function;
- candidates are every ``self.<attr>`` read and every free-variable
  name read inside the miss scope (the values that shape the program
  being built), plus any read of a registered static flag anywhere in
  the enclosing function;
- each candidate must appear in the key expression — as an attribute,
  a name, or via the ``COVERED_BY`` map (e.g. ``self.mesh`` is fully
  determined by the static device layout already in the key).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from rdma_paxos_tpu.analysis.engine import (
    Finding, SourceTree, attr_chain)

PASS_ID = "cache-key"

# attribute names that are static program-shaping flags wherever they
# are read in a builder (new flags get added HERE, once)
STATIC_FLAGS: Set[str] = {
    "cfg", "R", "_mode", "_use_pallas", "_interpret", "_fanout",
    "_audit", "_telemetry", "_mesh_key", "_txn",
}

# reads that are legitimately NOT in the key because another key
# component fully determines them: candidate -> acceptable witnesses
COVERED_BY: Dict[str, Tuple[str, ...]] = {
    # the replica/device mesh is constructed from (cfg, R) + the
    # engine mode / static device layout, both key components
    "mesh": ("_mode", "_mesh_key"),
}

# never program-shaping: cache plumbing and builder machinery
IGNORED: Set[str] = {
    "self", "STEP_CACHE", "_STEP_CACHE", "get", "dict",
}


def _store_sites(mod) -> List[ast.Assign]:
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if isinstance(t, ast.Subscript):
            chain = attr_chain(t.value)
            if chain and chain.split(".")[-1].endswith("STEP_CACHE"):
                out.append(node)
    return out


def _miss_scope(mod, store: ast.Assign) -> ast.AST:
    """Smallest enclosing If (the cache-miss guard), else function,
    else module."""
    func = mod.enclosing_function(store)
    for anc in mod.ancestors(store):
        if isinstance(anc, ast.If):
            return anc
        if anc is func:
            break
    return func if func is not None else mod.tree


def _key_expr(mod, store: ast.Assign) -> Optional[ast.AST]:
    """Resolve the key expression for a store site: the subscript's
    index if it is not a bare name, else the nearest preceding
    assignment to that name in the enclosing function/module."""
    sub = store.targets[0]
    idx = sub.slice
    if not isinstance(idx, ast.Name):
        return idx
    key_name = idx.id
    func = mod.enclosing_function(store) or mod.tree
    best = None
    for node in ast.walk(func):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == key_name
                and node.lineno <= store.lineno):
            if best is None or node.lineno > best.lineno:
                best = node
    return best.value if best is not None else None


def _expr_tokens(expr: ast.AST) -> Set[str]:
    """Every attribute name, bare name, and string constant appearing
    in the key expression — the set of things the key 'contains'."""
    toks: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            toks.add(node.attr)
        elif isinstance(node, ast.Name):
            toks.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value,
                                                          str):
            toks.add(node.value)
    return toks


def _bound_names(scope: ast.AST) -> Set[str]:
    """Names assigned (or imported/bound) inside the scope — local
    plumbing like ``fn``/``kw``/loop vars, not inputs."""
    bound: Set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                bound.add((a.asname or a.name).split(".")[0])
    return bound


def _candidates(mod, scope: ast.AST, func,
                exclude: Set[str] = frozenset()) -> Dict[str, int]:
    """candidate name -> first line read. Self-attrs + free names in
    the miss scope; registered static-flag attrs anywhere in the
    enclosing function. ``exclude`` drops the key variable itself."""
    cands: Dict[str, int] = {}
    bound = _bound_names(scope) | set(exclude)
    call_heads: Set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Call):
            head = attr_chain(node.func)
            if head is not None and "." not in head:
                call_heads.add(head)

    def _see(name: str, line: int) -> None:
        if name in IGNORED or name in cands:
            return
        cands[name] = line

    for node in ast.walk(scope):
        if isinstance(node, ast.Attribute) and isinstance(node.ctx,
                                                         ast.Load):
            if isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                _see(node.attr, node.lineno)
        elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                       ast.Load):
            if node.id in bound or node.id in call_heads:
                continue
            _see(node.id, node.lineno)
    if func is not None:
        for node in ast.walk(func):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in STATIC_FLAGS):
                if node.attr not in cands:
                    cands[node.attr] = node.lineno
    return cands


def _covered(name: str, toks: Set[str]) -> bool:
    if name in toks:
        return True
    return any(w in toks for w in COVERED_BY.get(name, ()))


def default_scope(tree: SourceTree) -> List[str]:
    """Every package file mentioning STEP_CACHE stores is a builder
    module — derived, not listed, so new builder homes are
    auto-covered."""
    out = []
    for rel in tree.files():
        if "STEP_CACHE[" in tree.module(rel).text:
            out.append(rel)
    return out


def run(tree: SourceTree,
        scope: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for rel in (scope or default_scope(tree)):
        mod = tree.module(rel)
        for store in _store_sites(mod):
            key = _key_expr(mod, store)
            if key is None:
                findings.append(Finding(
                    file=rel, line=store.lineno, pass_id=PASS_ID,
                    message="STEP_CACHE store whose key expression "
                            "cannot be resolved — use a local "
                            "``key = (...)`` tuple"))
                continue
            toks = _expr_tokens(key)
            miss = _miss_scope(mod, store)
            func = mod.enclosing_function(store)
            idx = store.targets[0].slice
            keyvars = ({idx.id} if isinstance(idx, ast.Name)
                       else set())
            for name, line in sorted(
                    _candidates(mod, miss, func,
                                exclude=keyvars).items(),
                    key=lambda kv: kv[1]):
                if not _covered(name, toks):
                    findings.append(Finding(
                        file=rel, line=line, pass_id=PASS_ID,
                        message="builder reads %r but the STEP_CACHE "
                                "key (line %d) does not carry it — "
                                "two clusters differing in %r would "
                                "share one compiled program" %
                                (name, store.lineno, name)))
    return findings
