"""graftlint engine: source-tree model, Finding, baseline, pass runner.

Pure stdlib (ast + re) — the analyzer must run in CI before anything
heavy imports, and must never import jax itself. Python 3.10
compatible: ``baseline.toml`` is read by a minimal TOML-subset parser
(tomllib only exists from 3.11), which covers exactly the grammar the
baseline uses — ``[[suppress]]`` table arrays of string key/values.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

PASS_IDS = ("jit-purity", "cache-key", "lock-discipline",
            "determinism", "thread-hygiene")


def repo_root() -> str:
    """The directory holding the ``rdma_paxos_tpu`` package (the
    analyzer runs on its own checkout unless told otherwise)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def default_baseline_path(root: Optional[str] = None) -> str:
    root = root or repo_root()
    return os.path.join(root, "rdma_paxos_tpu", "analysis",
                        "baseline.toml")


@dataclass(frozen=True)
class Finding:
    """One invariant violation at an exact source location."""

    file: str        # repo-relative, forward slashes
    line: int
    pass_id: str
    message: str

    def __str__(self) -> str:
        return "%s:%d: [%s] %s" % (self.file, self.line, self.pass_id,
                                   self.message)

    def to_dict(self) -> dict:
        return dict(file=self.file, line=self.line,
                    pass_id=self.pass_id, message=self.message)


class ModuleSrc:
    """One parsed source file: text, lines, AST with parent links."""

    def __init__(self, root: str, rel: str):
        self.rel = rel
        self.path = os.path.join(root, rel)
        with open(self.path, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=rel)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    @property
    def dotted(self) -> str:
        """``rdma_paxos_tpu/obs/audit.py`` -> ``rdma_paxos_tpu.obs.audit``
        (packages map to their ``__init__``'s dotted name)."""
        mod = self.rel[:-3].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        return mod

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None


class SourceTree:
    """Lazy parsed view of the package source under ``root``."""

    PACKAGE = "rdma_paxos_tpu"

    def __init__(self, root: Optional[str] = None):
        self.root = os.path.abspath(root or repo_root())
        self._cache: Dict[str, ModuleSrc] = {}

    def files(self) -> List[str]:
        out = []
        pkg = os.path.join(self.root, self.PACKAGE)
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          self.root)
                    out.append(rel.replace(os.sep, "/"))
        return out

    def has(self, rel: str) -> bool:
        return os.path.exists(os.path.join(self.root, rel))

    def module(self, rel: str) -> ModuleSrc:
        rel = rel.replace(os.sep, "/")
        m = self._cache.get(rel)
        if m is None:
            m = self._cache[rel] = ModuleSrc(self.root, rel)
        return m

    def rel_of_dotted(self, dotted: str) -> Optional[str]:
        """Dotted module name -> repo-relative path, or None when the
        name does not resolve inside the package tree."""
        base = dotted.replace(".", "/")
        for cand in (base + ".py", base + "/__init__.py"):
            if self.has(cand):
                return cand
        return None


# ---------------------------------------------------------------------------
# baseline (justified suppressions)
# ---------------------------------------------------------------------------

@dataclass
class Suppression:
    pass_id: str
    file: str
    contains: str
    reason: str = ""
    # optional second selector: when set, BOTH substrings must match.
    # Lock-discipline entries use it to pin (field, function) pairs —
    # contains="read of '_tickets'" + symbol="block in step()" — so a
    # FUTURE unlocked access to a different field in the same function
    # is never silently excused by a triaged peek's entry.
    symbol: str = ""
    used: int = field(default=0, compare=False)

    def matches(self, f: Finding) -> bool:
        return (f.pass_id == self.pass_id and f.file == self.file
                and self.contains in f.message
                and (not self.symbol or self.symbol in f.message))


_KV_RE = re.compile(r'^\s*([A-Za-z_][\w-]*)\s*=\s*"((?:[^"\\]|\\.)*)"\s*$')


def _toml_unescape(s: str) -> str:
    return (s.replace("\\\\", "\x00").replace('\\"', '"')
            .replace("\\n", "\n").replace("\\t", "\t")
            .replace("\x00", "\\"))


def _toml_escape(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n").replace("\t", "\\t"))


def load_baseline(path: str) -> List[Suppression]:
    """Parse the TOML subset the baseline uses: comments, blank lines,
    ``[[suppress]]`` headers, and ``key = "string"`` pairs. Anything
    else is an error — the file is machine-written and hand-justified,
    and a silent partial parse would silently drop suppressions."""
    if not os.path.exists(path):
        return []
    entries: List[Suppression] = []
    cur: Optional[dict] = None
    with open(path, "r", encoding="utf-8") as f:
        for ln, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[suppress]]":
                cur = {}
                entries.append(cur)  # type: ignore[arg-type]
                continue
            m = _KV_RE.match(line)
            if m and cur is not None:
                cur[m.group(1)] = _toml_unescape(m.group(2))
                continue
            raise ValueError(
                "%s:%d: unsupported baseline syntax: %r" %
                (path, ln, line))
    out = []
    for e in entries:
        missing = {"pass", "file", "contains"} - set(e)
        if missing:
            raise ValueError(
                "%s: [[suppress]] entry missing keys %s: %r" %
                (path, sorted(missing), e))
        out.append(Suppression(pass_id=e["pass"], file=e["file"],
                               contains=e["contains"],
                               symbol=e.get("symbol", ""),
                               reason=e.get("reason", "")))
    return out


def render_baseline(entries: Sequence[Suppression],
                    header: str = "") -> str:
    parts = []
    if header:
        parts.append("\n".join("# " + h if h else "#"
                               for h in header.splitlines()))
        parts.append("")
    for e in entries:
        parts.append("[[suppress]]")
        parts.append('pass = "%s"' % _toml_escape(e.pass_id))
        parts.append('file = "%s"' % _toml_escape(e.file))
        parts.append('contains = "%s"' % _toml_escape(e.contains))
        if e.symbol:
            parts.append('symbol = "%s"' % _toml_escape(e.symbol))
        parts.append('reason = "%s"' % _toml_escape(
            e.reason or "TODO: justify this suppression"))
        parts.append("")
    return "\n".join(parts).rstrip() + "\n"


# ---------------------------------------------------------------------------
# pass registry + runner
# ---------------------------------------------------------------------------

def _passes() -> Dict[str, object]:
    # imported here (not at module top) so ``engine`` stays importable
    # from the pass modules without a cycle
    from rdma_paxos_tpu.analysis import (
        cachekey, determinism, hygiene, locks, purity)
    return {
        "jit-purity": purity.run,
        "cache-key": cachekey.run,
        "lock-discipline": locks.run,
        "determinism": determinism.run,
        "thread-hygiene": hygiene.run,
    }


@dataclass
class Report:
    findings: List[Finding]                  # NOT baselined — failures
    suppressed: List[Tuple[Finding, Suppression]]
    unused_suppressions: List[Suppression]
    all_findings: List[Finding]

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return dict(
            ok=self.ok,
            findings=[f.to_dict() for f in self.findings],
            suppressed=[
                dict(finding=f.to_dict(), reason=s.reason)
                for f, s in self.suppressed],
            unused_suppressions=[
                dict(pass_id=s.pass_id, file=s.file,
                     contains=s.contains, reason=s.reason)
                for s in self.unused_suppressions])

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)


def run_analysis(root: Optional[str] = None,
                 passes: Optional[Sequence[str]] = None,
                 baseline: Optional[str] = "auto") -> Report:
    """Run the requested passes (default: all five) over the tree at
    ``root`` and fold the baseline in. ``baseline`` is a path, None
    (no suppression), or "auto" (the checked-in baseline.toml)."""
    tree = SourceTree(root)
    registry = _passes()
    ids = list(passes or PASS_IDS)
    unknown = [p for p in ids if p not in registry]
    if unknown:
        raise ValueError("unknown pass(es): %s (known: %s)" %
                         (unknown, list(registry)))
    all_findings: List[Finding] = []
    for pid in ids:
        all_findings.extend(registry[pid](tree))
    all_findings.sort(key=lambda f: (f.file, f.line, f.pass_id))

    if baseline == "auto":
        baseline = default_baseline_path(tree.root)
    sups = load_baseline(baseline) if baseline else []
    live: List[Finding] = []
    suppressed: List[Tuple[Finding, Suppression]] = []
    for f in all_findings:
        hit = next((s for s in sups if s.matches(f)), None)
        if hit is None:
            live.append(f)
        else:
            hit.used += 1
            suppressed.append((f, hit))
    unused = [s for s in sups if s.used == 0
              and (passes is None or s.pass_id in ids)]
    return Report(findings=live, suppressed=suppressed,
                  unused_suppressions=unused,
                  all_findings=all_findings)


# ---------------------------------------------------------------------------
# small shared AST helpers the passes use
# ---------------------------------------------------------------------------

def attr_chain(node: ast.AST) -> Optional[str]:
    """``self.cluster._host_lock`` -> "self.cluster._host_lock";
    None for expressions that are not a pure Name/Attribute chain."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Local name -> dotted module for plain ``import x [as y]``
    statements anywhere in the module (function-level included)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
    return out
