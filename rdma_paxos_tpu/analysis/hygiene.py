"""thread-hygiene pass: spawned threads have a stop/join path, and
HTTP serving handlers answer errors rather than die.

Two rules, both distilled from review riders:

1. every ``threading.Thread(...)`` construction either passes
   ``daemon=True`` (process exit reaps it) or the module contains a
   ``.join(`` call on the attribute/name the thread is bound to (an
   explicit stop path). A non-daemon thread with no join wedges
   interpreter shutdown the first time its loop blocks.

2. every ``do_*`` method of a ``*RequestHandler`` subclass wraps its
   body in ``try`` at the top level — the PR 12 rule: a probe/metrics
   endpoint answers 500, it never kills its own serving thread.
"""

from __future__ import annotations

import ast
from typing import List

from rdma_paxos_tpu.analysis.engine import (
    Finding, SourceTree, attr_chain)

PASS_ID = "thread-hygiene"


def _thread_calls(mod) -> List[ast.Call]:
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain in ("threading.Thread", "Thread"):
                out.append(node)
    return out


def _is_daemon(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            return (isinstance(kw.value, ast.Constant)
                    and bool(kw.value.value))
    return False


def _daemon_assigned(mod) -> bool:
    """``t.daemon = True`` set after construction counts too."""
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and node.targets[0].attr == "daemon"
                and isinstance(node.value, ast.Constant)
                and bool(node.value.value)):
            return True
    return False


def _thread_names(mod) -> set:
    """Attr/name targets Thread objects are assigned to in this
    module (``self._rb_thread = Thread(...)`` -> ``_rb_thread``)."""
    names = set()
    for call in _thread_calls(mod):
        parent = mod.parent(call)
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                if isinstance(t, ast.Attribute):
                    names.add(t.attr)
                elif isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _has_join(mod) -> bool:
    """A ``.join(...)`` call counts as a stop path when its receiver
    is a bare local name (the ``t, self._x = self._x, None; t.join()``
    temp idiom) or an attribute matching a name a Thread was assigned
    to — so an unrelated ``self._sep.join(parts)`` string join can
    never bless an unreaped thread."""
    tnames = _thread_names(mod)
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            recv = node.func.value
            if isinstance(recv, ast.Name):
                return True
            if isinstance(recv, ast.Attribute) and recv.attr in tnames:
                return True
    return False


def _handler_findings(mod, rel: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if not any(
                (attr_chain(b) or "").split(".")[-1].endswith(
                    "RequestHandler")
                for b in node.bases):
            continue
        for item in node.body:
            if (isinstance(item, ast.FunctionDef)
                    and item.name.startswith("do_")):
                body = [s for s in item.body
                        if not (isinstance(s, ast.Expr)
                                and isinstance(s.value, ast.Constant))]
                if not (len(body) == 1
                        and isinstance(body[0], ast.Try)):
                    out.append(Finding(
                        file=rel, line=item.lineno, pass_id=PASS_ID,
                        message="HTTP handler %s.%s must wrap its "
                                "whole body in try/except — serving "
                                "handlers answer errors (500), they "
                                "never kill the serving thread" %
                                (node.name, item.name)))
    return out


def run(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []
    for rel in tree.files():
        mod = tree.module(rel)
        if "Thread" in mod.text:
            daemon_later = _daemon_assigned(mod)
            for call in _thread_calls(mod):
                if _is_daemon(call) or daemon_later:
                    continue
                if _has_join(mod):
                    continue
                findings.append(Finding(
                    file=rel, line=call.lineno, pass_id=PASS_ID,
                    message="threading.Thread without daemon=True or "
                            "a .join() stop path in this module — "
                            "the thread has no reaping story"))
        if "RequestHandler" in mod.text:
            findings.extend(_handler_findings(mod, rel))
    return findings
