"""``python -m rdma_paxos_tpu.analysis`` — the graftlint CLI.

Exit 0 when every finding is baselined (or none exist), exit 1
otherwise, printing one ``file:line: [pass] message`` per live
finding. ``--json`` writes the full report (live + suppressed +
unused suppressions) for the CI artifact; ``--write-baseline``
records the current live findings as suppression stubs to be
hand-justified (the triage workflow).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from rdma_paxos_tpu.analysis.engine import (
    PASS_IDS, Suppression, default_baseline_path, render_baseline,
    run_analysis)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rdma_paxos_tpu.analysis",
        description="graftlint: repo-native static analysis "
                    "(jit purity, cache-key completeness, lock "
                    "discipline, determinism, thread hygiene)")
    ap.add_argument("passes", nargs="*", metavar="PASS",
                    help="subset of passes to run (default: all of "
                         "%s)" % (", ".join(PASS_IDS)))
    ap.add_argument("--root", default=None,
                    help="repo root to analyze (default: this "
                         "checkout)")
    ap.add_argument("--baseline", default=None,
                    help="suppression file (default: the checked-in "
                         "analysis/baseline.toml)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, suppressions ignored")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the findings report as JSON")
    ap.add_argument("--write-baseline", action="store_true",
                    help="append the current live findings to the "
                         "baseline as to-be-justified suppressions")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    baseline = (None if args.no_baseline
                else (args.baseline or "auto"))
    report = run_analysis(root=args.root,
                          passes=args.passes or None,
                          baseline=baseline)
    dt = time.monotonic() - t0

    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(report.to_json())

    for f in report.findings:
        print(f)
    if not args.quiet:
        for s in report.unused_suppressions:
            print("note: unused suppression [%s] %s (%r)" %
                  (s.pass_id, s.file, s.contains))
        print("graftlint: %d finding(s), %d suppressed, %d pass(es) "
              "in %.2fs" % (len(report.findings),
                            len(report.suppressed),
                            len(args.passes or PASS_IDS), dt))

    if args.write_baseline and report.findings:
        path = args.baseline or default_baseline_path(args.root)
        stubs = [Suppression(pass_id=f.pass_id, file=f.file,
                             contains=f.message, reason="")
                 for f in report.findings]
        # APPEND the stubs: the checked-in baseline carries curated
        # comments and section headers that a load/render round-trip
        # would destroy
        exists = os.path.exists(path)
        with open(path, "a", encoding="utf-8") as fh:
            if not exists:
                fh.write("# graftlint baseline — every entry needs a "
                         "one-line justification.\n# Entries match "
                         "by (pass, file, contains [, symbol]) "
                         "message substrings.\n")
            fh.write("\n" + render_baseline(stubs))
        print("appended %d suppression stub(s) to %s — justify them" %
              (len(stubs), path))

    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
