"""Vectorized host data plane — the interpreter touches each command
O(1/window), not O(1).

REDIS_r05's structural budget put 3-5 us of every 8.5 us end-to-end SET
in Python driver host work: per-entry loops in window encode
(``pack_rows``), window decode (``decode_window``), frame assembly, and
the drivers' per-connection replay/ack release. This module is the one
batched implementation all three drivers share (``ClusterDriver``,
``ShardedClusterDriver``, ``NodeDaemon``'s ``HostReplicaDriver``) — the
host-side half of the SmartNIC-offload design pole (PAPERS.md
2503.18093: move the serving data plane off the general-purpose
interpreter):

* **encode** (:func:`pack_window`) — one ``b"".join`` + one fancy-index
  scatter packs a whole window of payloads into the staging buffers;
  metadata columns land in four column writes instead of four scalar
  stores per entry.
* **decode** (:func:`decode_batch`) — one boolean-mask gather compacts
  a fetched window's client payloads into ONE ``bytes`` blob with a
  cumsum offset table (:class:`ReplayBatch`); no per-entry bytes object
  is ever allocated on the hot path.
* **frames** (:meth:`ReplayBatch.frames`) — the store-ready framed blob
  is built by scattering headers + payload into one preallocated array
  over the precomputed offset table.
* **replay/ack** (:func:`replay_plan`) — per-connection run
  coalescing and the own-entry ack frontier are derived from grouped
  index arrays; each replayed op is ONE slice of the compacted blob.

Every operation keeps a **scalar reference implementation** (the exact
pre-vectorization loops): ``tests/test_hostpath.py`` pins the two
byte-identical on recorded workloads, and the CI perf smoke
(``benchmarks/hostpath_bench.py``) enforces vectorized >= scalar so a
future PR cannot silently reintroduce a per-entry loop. The module is
deliberately numpy-only — nothing here may import jax.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from rdma_paxos_tpu.consensus.log import (
    EntryType, M_CONN, M_GEN, M_GIDX, M_LEN, M_REQID, M_TERM, M_TYPE)

# module-wide switch between the vectorized hot path and the scalar
# reference loops — flipped by the host_path_speedup A/B benches
# (alternating best-of rounds); tests pin the two bit-identical, so
# the flag is a pure performance knob, never a semantics one
VECTORIZED = True


def set_vectorized(flag: bool) -> bool:
    """Select the vectorized (True) or scalar-reference (False) host
    data plane; returns the previous setting."""
    global VECTORIZED
    prev = VECTORIZED
    VECTORIZED = bool(flag)
    return prev


def ragged_arange(lens: np.ndarray) -> np.ndarray:
    """``concatenate([arange(l) for l in lens])`` without the loop."""
    total = int(lens.sum())
    if not total:
        return np.zeros(0, np.int64)
    ends = np.cumsum(lens)
    return (np.arange(total, dtype=np.int64)
            - np.repeat(ends - lens, lens))


# ---------------------------------------------------------------------------
# window encode
# ---------------------------------------------------------------------------

def pack_window(du8: np.ndarray, meta: np.ndarray,
                take: Sequence[Tuple], slot_bytes: int,
                gen: Optional[int] = None) -> int:
    """Pack ``take`` rows of ``(etype, conn, req, payload)`` into one
    window's staging buffers (``du8``: the ``[B, slot_bytes]`` u8 view
    of the payload words, ``meta``: ``[B, META_W]`` i32). Rows are
    assumed pre-zeroed (the StagingPool contract). Returns the number
    of rows written."""
    n = len(take)
    if not n:
        return 0
    if VECTORIZED:
        _pack_vec(du8, meta, take, slot_bytes, gen)
    else:
        _pack_scalar(du8, meta, take, slot_bytes, gen)
    return n


def _pack_scalar(du8, meta, take, slot_bytes, gen) -> None:
    """The pre-vectorization per-entry loop — the bit-identity
    reference (and the CI smoke's scalar baseline)."""
    for i, (t, conn, req, payload) in enumerate(take):
        ln = len(payload)
        if ln > slot_bytes:
            raise ValueError("payload exceeds slot capacity; "
                             "fragment first")
        if ln:
            du8[i, :ln] = np.frombuffer(payload, np.uint8)
        row = meta[i]
        row[M_TYPE] = t
        row[M_CONN] = conn
        row[M_REQID] = req
        row[M_LEN] = ln
        if gen is not None:
            row[M_GEN] = gen


def _pack_vec(du8, meta, take, slot_bytes, gen) -> None:
    n = len(take)
    cols = np.array([(t, c, q) for (t, c, q, _p) in take], np.int32)
    payloads = [p for (_t, _c, _q, p) in take]
    lens = np.fromiter(map(len, payloads), np.int64, count=n)
    if int(lens.max()) > slot_bytes:
        raise ValueError("payload exceeds slot capacity; "
                         "fragment first")
    meta[:n, M_TYPE] = cols[:, 0]
    meta[:n, M_CONN] = cols[:, 1]
    meta[:n, M_REQID] = cols[:, 2]
    meta[:n, M_LEN] = lens
    if gen is not None:
        meta[:n, M_GEN] = gen
    total = int(lens.sum())
    if total:
        src = np.frombuffer(b"".join(payloads), np.uint8)
        row = du8.shape[1]
        pos = (np.repeat(np.arange(n, dtype=np.int64) * row, lens)
               + ragged_arange(lens))
        du8.reshape(-1)[pos] = src


# ---------------------------------------------------------------------------
# window decode — the columnar replay batch
# ---------------------------------------------------------------------------

class ReplayBatch:
    """One decoded window's client entries, held COLUMNAR: per-entry
    metadata as numpy columns plus ONE compacted payload blob with a
    cumsum offset table (entry i's payload is
    ``blob[offs[i]:offs[i + 1]]``). The hot path (store frames, replay
    run coalescing, ack frontiers) consumes the columns directly;
    :meth:`tuples` materializes the legacy per-entry tuple form for
    tests and cold consumers."""

    __slots__ = ("types", "conns", "reqs", "gens", "lens", "blob",
                 "offs", "terms", "gidx")

    def __init__(self, types, conns, reqs, gens, lens, blob, offs,
                 terms=None, gidx=None):
        self.types = types        # [n] i32
        self.conns = conns        # [n] i32
        self.reqs = reqs          # [n] i32
        self.gens = gens          # [n] i32 (M_GEN — NodeDaemon acks)
        self.lens = lens          # [n] i64, clipped to the slot width
        self.blob = blob          # bytes — compacted payloads
        self.offs = offs          # [n + 1] i64 cumsum offset table
        # log coordinates (streams/: scan cuts, watch resume tokens,
        # CDC records) — None on plan-only batches built outside the
        # decode path, where no wm rows exist to source them from
        self.terms = terms        # [n] i64 M_TERM, or None
        self.gidx = gidx          # [n] i64 absolute index, or None

    def __len__(self) -> int:
        return len(self.types)

    def tuples(self) -> List[Tuple[int, int, int, bytes]]:
        """Materialize ``[(etype, conn, req, payload), ...]`` — the
        legacy replay-stream element form."""
        t, c, q, o, b = (self.types, self.conns, self.reqs, self.offs,
                         self.blob)
        return [(int(t[i]), int(c[i]), int(q[i]), b[o[i]:o[i + 1]])
                for i in range(len(t))]

    def slice(self, start: int) -> "ReplayBatch":
        """The tail batch from entry ``start`` on. The FULL blob is
        kept and the offset table stays ABSOLUTE (``offs[0]`` is the
        tail's first byte, not 0) — entry ``i``'s payload remains
        ``blob[offs[i]:offs[i + 1]]``, so every consumer must slice
        through the offset table (``frames_from_cols`` detects the
        non-compacted case via ``len(blob) != lens.sum()`` and
        gathers)."""
        if start <= 0:
            return self
        return ReplayBatch(
            self.types[start:], self.conns[start:],
            self.reqs[start:], self.gens[start:],
            self.lens[start:], self.blob, self.offs[start:],
            None if self.terms is None else self.terms[start:],
            None if self.gidx is None else self.gidx[start:])

    def frames(self) -> bytes:
        """Store-ready framed blob ``([u32 len][u8 etype][u32 conn]
        [payload])*`` built over the precomputed offset table — one
        output allocation, zero per-record Python (byte-identical to
        the legacy ``assemble_frames``; pinned golden by test)."""
        return frames_from_cols(self.types, self.conns, self.lens,
                                self.blob, self.offs)


def frames_from_cols(types, conns, lens, blob: bytes, offs) -> bytes:
    """See :meth:`ReplayBatch.frames` — exposed so the legacy
    ``assemble_frames(types, conns, lens, raw, idxs)`` signature can
    delegate here after compacting its payloads."""
    n = len(types)
    if not n:
        return b""
    lens = np.asarray(lens, np.int64)
    rec = 9 + lens                              # header + payload
    out = np.zeros(int(rec.sum()), np.uint8)
    starts = np.cumsum(rec) - rec
    out[starts[:, None] + np.arange(4)] = (
        (lens + 5).astype("<u4").view(np.uint8).reshape(n, 4))
    out[starts + 4] = np.asarray(types).astype(np.uint8)
    out[starts[:, None] + 5 + np.arange(4)] = (
        np.asarray(conns).astype("<i4").view(np.uint8).reshape(n, 4))
    total = int(lens.sum())
    if total:
        src = np.frombuffer(blob, np.uint8)
        if len(src) != total:                   # non-compacted offsets
            o = np.asarray(offs, np.int64)
            src = src[np.repeat(o[:n], lens) + ragged_arange(lens)]
        out[np.repeat(starts + 9, lens) + ragged_arange(lens)] = src
    return out.tobytes()


def decode_batch(wm: np.ndarray, wd: np.ndarray, n: int,
                 rebase: int = 0) -> Optional[ReplayBatch]:
    """Decode the first ``n`` fetched entries of a window into a
    :class:`ReplayBatch` of its CLIENT entries (CONNECT/SEND/CLOSE —
    NOOP/CONFIG rows never reach the app); None when the window holds
    no client entries. ``rebase`` is the caller's accumulated rollover
    total at decode time — added to the raw ``M_GIDX`` column so the
    batch carries ABSOLUTE log indices (decode runs before the same
    finish()'s rebase check, so the raw indices are consistent with
    the rebase total the caller holds)."""
    if n <= 0:
        return None
    if VECTORIZED:
        return _decode_vec(wm, wd, n, rebase)
    return _decode_scalar(wm, wd, n, rebase)


def _client_rows(wm, n):
    types = wm[:n, M_TYPE]
    client = ((types >= int(EntryType.CONNECT))
              & (types <= int(EntryType.CLOSE)))
    return types, np.nonzero(client)[0]


def _decode_scalar(wm, wd, n, rebase=0) -> Optional[ReplayBatch]:
    """Per-entry reference decode (the pre-vectorization loop shape):
    one bytes slice per entry, joined — bit-identical columns/blob."""
    types, idxs = _client_rows(wm, n)
    if not idxs.size:
        return None
    raw = np.ascontiguousarray(wd[:n]).view(np.uint8).reshape(n, -1)
    row = raw.shape[1]
    buf = raw.tobytes()
    parts, lens = [], []
    for j in idxs:
        ln = min(int(wm[j, M_LEN]), row)
        o = int(j) * row
        parts.append(buf[o:o + ln])
        lens.append(ln)
    lens_a = np.asarray(lens, np.int64)
    offs = np.zeros(len(idxs) + 1, np.int64)
    np.cumsum(lens_a, out=offs[1:])
    return ReplayBatch(
        wm[idxs, M_TYPE].astype(np.int32),
        wm[idxs, M_CONN].astype(np.int32),
        wm[idxs, M_REQID].astype(np.int32),
        wm[idxs, M_GEN].astype(np.int32),
        lens_a, b"".join(parts), offs,
        wm[idxs, M_TERM].astype(np.int64),
        wm[idxs, M_GIDX].astype(np.int64) + int(rebase))


def _decode_vec(wm, wd, n, rebase=0) -> Optional[ReplayBatch]:
    types, idxs = _client_rows(wm, n)
    if not idxs.size:
        return None
    raw = np.ascontiguousarray(wd[:n]).view(np.uint8).reshape(n, -1)
    row = raw.shape[1]
    full = idxs.size == n                 # every row is a client entry
    sel = (lambda col: wm[:n, col]) if full else (
        lambda col: wm[idxs, col])
    lens = np.minimum(sel(M_LEN).astype(np.int64), row)
    keep = np.arange(row, dtype=np.int64) < lens[:, None]
    # ONE compacted pass; the full-window case (the common one under
    # SEND-only traffic) skips the row gather entirely
    blob = (raw[keep] if full else raw[idxs][keep]).tobytes()
    offs = np.zeros(idxs.size + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    return ReplayBatch(
        sel(M_TYPE).astype(np.int32),
        sel(M_CONN).astype(np.int32),
        sel(M_REQID).astype(np.int32),
        sel(M_GEN).astype(np.int32),
        lens, blob, offs,
        sel(M_TERM).astype(np.int64),
        sel(M_GIDX).astype(np.int64) + int(rebase))


# ---------------------------------------------------------------------------
# the lazy replay stream
# ---------------------------------------------------------------------------

class LazyReplayStream:
    """List-compatible committed-entry stream backed by
    :class:`ReplayBatch` windows. The hot path appends/consumes whole
    batches (O(1) Python per window); tests, models, and recovery
    paths that index/slice/compare see the legacy tuple view,
    materialized lazily and cached."""

    __slots__ = ("_flat", "_tail", "_tail_n")

    def __init__(self, initial=None):
        self._flat: list = list(initial) if initial else []
        self._tail: List[ReplayBatch] = []
        self._tail_n = 0

    def append_batch(self, batch: ReplayBatch) -> None:
        self._tail.append(batch)
        self._tail_n += len(batch)

    def append(self, entry) -> None:
        self._materialize()
        self._flat.append(entry)

    def extend(self, entries) -> None:
        self._materialize()
        self._flat.extend(entries)

    def __len__(self) -> int:
        return len(self._flat) + self._tail_n

    def _materialize(self) -> list:
        if self._tail:
            for b in self._tail:
                self._flat.extend(b.tuples())
            self._tail = []
            self._tail_n = 0
        return self._flat

    def __getitem__(self, i):
        return self._materialize()[i]

    def __iter__(self):
        return iter(self._materialize())

    def __eq__(self, other):
        if isinstance(other, LazyReplayStream):
            other = other._materialize()
        return self._materialize() == other

    def __ne__(self, other):
        return not self.__eq__(other)

    def __repr__(self):
        return f"LazyReplayStream(n={len(self)})"

    def segments_from(self, start: int):
        """Yield the entries ``[start, len(self))`` as consumable
        segments — :class:`ReplayBatch` objects where the cursor fell
        on (or inside) an unmaterialized batch, plus at most one
        leading plain tuple list. The drivers' batched replay/ack path
        consumes these without ever materializing tuples."""
        segs = []
        flat_n = len(self._flat)
        if start < flat_n:
            segs.append(self._flat[start:])
            start = flat_n
        off = start - flat_n
        for b in self._tail:
            nb = len(b)
            if off >= nb:
                off -= nb
                continue
            segs.append(b.slice(off) if off else b)
            off = 0
        return segs


def stream_copy(stream) -> "LazyReplayStream":
    """Snapshot a donor's replay stream into a fresh lazy stream (the
    recipient's copy diverges from the donor's from here on — and must
    stay batch-appendable for the vectorized decode path). The one
    copy rule for every recovery path (repair installs, chaos
    restarts). A lazy donor is copied STRUCTURALLY — batches are
    immutable, so sharing them keeps the log coordinates (terms/gidx)
    the streams/ subsystem reads, and a later donor ``_materialize``
    cannot reach into the copy."""
    if isinstance(stream, LazyReplayStream):
        out = LazyReplayStream(stream._flat)
        out._tail = list(stream._tail)
        out._tail_n = stream._tail_n
        return out
    return LazyReplayStream(list(stream))


def extend_stream(stream, batch: ReplayBatch) -> None:
    """Append a decoded batch to a replay stream — batched when the
    slot holds a :class:`LazyReplayStream`, tuple-extended when a test
    or recovery path replaced it with a plain list."""
    if isinstance(stream, LazyReplayStream):
        stream.append_batch(batch)
    else:
        stream.extend(batch.tuples())


# ---------------------------------------------------------------------------
# replay/ack planning (the drivers' per-connection release)
# ---------------------------------------------------------------------------

def replay_plan(seg, own_mask: np.ndarray, want_ops: bool = True
                ) -> Tuple[int, List[Tuple[int, int, bytes]]]:
    """One window's apply plan: ``(own_max, ops)`` where ``own_max``
    is the highest req of this replica's OWN entries (-1 when none —
    the ack-release frontier) and ``ops`` is the remote replay
    sequence with consecutive same-connection SENDs coalesced into one
    ``(SEND, conn, joined_payload)`` op — byte-stream identical to the
    per-entry loop it replaces (own entries never break a run; any
    non-SEND does). ``seg`` is a :class:`ReplayBatch`.
    ``want_ops=False`` (a dirty/absent app: nothing will be replayed)
    skips the remote compaction entirely and returns only the ack
    frontier."""
    if not want_ops:
        own_idx = np.flatnonzero(own_mask)
        return (int(seg.reqs[own_idx[-1]]) if own_idx.size else -1,
                [])
    if VECTORIZED:
        return _plan_vec(seg, own_mask)
    return _plan_scalar(seg, own_mask)


def _plan_scalar(seg, own_mask):
    """The drivers' original per-entry loop, as a pure plan — the
    bit-identity reference."""
    own_max = -1
    ops: list = []
    run_conn = -1
    run_parts: list = []

    def flush():
        nonlocal run_conn, run_parts
        if run_conn >= 0 and run_parts:
            ops.append((int(EntryType.SEND), run_conn,
                        b"".join(run_parts)))
        run_conn, run_parts = -1, []

    for i, (etype, conn, req, payload) in enumerate(seg.tuples()):
        if not own_mask[i]:
            if etype == int(EntryType.SEND):
                if conn != run_conn:
                    flush()
                    run_conn = conn
                run_parts.append(payload)
            else:
                flush()
                ops.append((etype, conn, payload))
        else:
            own_max = req
    flush()
    return own_max, ops


def _plan_vec(seg, own_mask):
    own_idx = np.flatnonzero(own_mask)
    own_max = int(seg.reqs[own_idx[-1]]) if own_idx.size else -1
    rem = np.flatnonzero(~own_mask)
    if not rem.size:
        return own_max, []
    t_r = seg.types[rem]
    c_r = seg.conns[rem]
    l_r = seg.lens[rem]
    if rem.size == len(seg):
        blob_r, off_r = seg.blob, seg.offs
    else:
        src = np.frombuffer(seg.blob, np.uint8)
        pos = np.repeat(seg.offs[rem], l_r) + ragged_arange(l_r)
        blob_r = src[pos].tobytes()
        off_r = np.zeros(rem.size + 1, np.int64)
        np.cumsum(l_r, out=off_r[1:])
    is_send = t_r == int(EntryType.SEND)
    brk = np.empty(rem.size, bool)
    brk[0] = True
    if rem.size > 1:
        brk[1:] = (~is_send[1:] | ~is_send[:-1]
                   | (c_r[1:] != c_r[:-1]))
    starts = np.flatnonzero(brk)
    ends = np.append(starts[1:], rem.size)
    return own_max, [
        (int(t_r[s]), int(c_r[s]),
         blob_r[off_r[s]:off_r[e]])
        for s, e in zip(starts, ends)]


def plan_segment(seg, own_of, want_ops: bool = True
                 ) -> Tuple[int, list, int]:
    """Plan one stream segment (ReplayBatch OR a plain tuple list —
    the post-recovery fallback): returns ``(own_max, ops,
    n_remote)``. ``own_of(conns, gens)`` maps the columns to the
    own-entry boolean mask; ``want_ops=False`` skips building the
    replay ops (see :func:`replay_plan`)."""
    if isinstance(seg, ReplayBatch):
        own = own_of(seg.conns, seg.gens)
        own_max, ops = replay_plan(seg, own, want_ops)
        return own_max, ops, int(len(seg) - own.sum())
    # plain tuples (a recovery path replaced the stream): wrap them
    # into a batch so the one plan implementation serves both
    n = len(seg)
    if not n:
        return -1, [], 0
    types = np.fromiter((e[0] for e in seg), np.int32, n)
    conns = np.fromiter((e[1] for e in seg), np.int32, n)
    reqs = np.fromiter((e[2] for e in seg), np.int32, n)
    lens = np.fromiter((len(e[3]) for e in seg), np.int64, n)
    offs = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    batch = ReplayBatch(types, conns, reqs, np.zeros(n, np.int32),
                        lens, b"".join(e[3] for e in seg), offs)
    own = own_of(batch.conns, batch.gens)
    own_max, ops = replay_plan(batch, own, want_ops)
    return own_max, ops, int(n - own.sum())


__all__ = [
    "LazyReplayStream", "ReplayBatch", "VECTORIZED", "decode_batch",
    "extend_stream", "frames_from_cols", "pack_window", "plan_segment",
    "ragged_arange", "replay_plan", "set_vectorized", "stream_copy",
]
