"""NodeDaemon — the per-host replica process for REAL multi-host clusters.

One of these runs on every host (the reference's per-machine app process
with ``interpose.so`` injected, ``benchmarks/run.sh:24-33``): it owns the
host's slice of the distributed consensus state (one replica on the local
chip), the proxy socket its interposed app connects to, the loopback replay
engine, the stable store, and the election timer.

Lock-step discipline: every loop iteration issues exactly TWO collective
programs in fixed order — the protocol step, then one window fetch — so
all hosts stay SPMD-consistent regardless of how their local values differ.
Hosts synchronize through the collectives themselves (a host that runs
ahead blocks in the next collective until peers arrive), exactly as the
reference's followers synchronize through RDMA completion semantics.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from rdma_paxos_tpu.config import ClusterConfig, LogConfig, TimeoutConfig
from rdma_paxos_tpu.consensus.log import (
    EntryType, M_CONN, M_LEN, M_REQID, M_TYPE)
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.proxy.proxy import PendingEvent, ProxyServer, ReplayEngine
from rdma_paxos_tpu.proxy.stablestore import HardState, StableStore
from rdma_paxos_tpu.runtime.host import HostReplicaDriver
from rdma_paxos_tpu.runtime.timers import ElectionTimer
from rdma_paxos_tpu.utils.codec import fragment
from rdma_paxos_tpu.utils.debug import ReplicaLog


class NodeDaemon:
    def __init__(self, cfg: LogConfig, *, process_id: int,
                 num_processes: int, coordinator: str,
                 workdir: str, app_port: Optional[int] = None,
                 timeout_cfg: Optional[TimeoutConfig] = None,
                 group_size: Optional[int] = None, seed: int = 0):
        self.cfg = cfg
        self.me = process_id
        self.hd = HostReplicaDriver(
            cfg, process_id=process_id, num_processes=num_processes,
            coordinator=coordinator, group_size=group_size)
        os.makedirs(workdir, exist_ok=True)
        self._lock = threading.Lock()
        self._is_leader = False
        self._submitq: List[Tuple[int, int, bytes, int]] = []
        self.inflight: collections.deque = collections.deque()
        self.submit_seq = 0
        self.applied = 0
        self.replicated_conns: set = set()
        self.passthrough_conns: set = set()
        self.sock_path = os.path.join(workdir, f"proxy{self.me}.sock")
        self.proxy = ProxyServer(self.sock_path, self.me, self._on_event)
        self.replay = (ReplayEngine("127.0.0.1", app_port)
                       if app_port else None)
        self.store = StableStore(
            os.path.join(workdir, f"replica{self.me}.db"))
        self.hard = HardState(
            os.path.join(workdir, f"replica{self.me}.db.hs"))
        # a RESTARTED daemon restores its persisted election state so it
        # cannot double-vote in a term it voted in before the crash
        # (collective — every daemon calls this during init, with zeros
        # when no prior state exists)
        hs = self.hard.load()
        self.hd.restore_hardstate(*(hs if hs is not None else (0, 0, -1)))
        self.log = ReplicaLog(
            os.path.join(workdir, f"replica{self.me}.log"))
        self.timer = ElectionTimer(timeout_cfg or TimeoutConfig(),
                                   seed=seed + process_id)
        self.last: Optional[Dict] = None

    # ------------------------------------------------------------------

    def _on_event(self, etype: int, conn_id: int, payload: bytes):
        with self._lock:
            if etype == int(EntryType.CONNECT):
                port = (int.from_bytes(payload[4:6], "big")
                        if len(payload) >= 6 else 0)
                if (self.replay is not None
                        and port in self.replay.local_ports):
                    self.passthrough_conns.add(conn_id)
                    return None
                if not self._is_leader:
                    return None
                self.replicated_conns.add(conn_id)
                payload = b""
            elif conn_id in self.passthrough_conns:
                if etype == int(EntryType.CLOSE):
                    self.passthrough_conns.discard(conn_id)
                return None
            elif conn_id not in self.replicated_conns:
                return None
            elif not self._is_leader:
                if etype == int(EntryType.CLOSE):
                    self.replicated_conns.discard(conn_id)
                    return None
                return -1
            if etype == int(EntryType.CLOSE):
                self.replicated_conns.discard(conn_id)
            frags = (fragment(payload, self.cfg.slot_bytes)
                     if etype == int(EntryType.SEND) else [payload])
            ev = PendingEvent(EntryType(etype), conn_id, payload)
            for f in frags:
                self.submit_seq += 1
                self._submitq.append((etype, conn_id, f, self.submit_seq))
            self.inflight.append((ev, self.submit_seq))
            return ev

    # ------------------------------------------------------------------

    def iterate(self) -> Dict:
        """One lock-step loop iteration (call in unison on every host)."""
        with self._lock:
            take = self._submitq[:self.cfg.batch_slots]
            self._submitq = self._submitq[self.cfg.batch_slots:]
        # (etype, conn, req_seq, payload) rows for make_input
        batch = [(t, c, s, f) for (t, c, f, s) in take]

        fire = False
        if not self._is_leader and self.timer.expired():
            fire = True
            self.timer.beat()

        res = self.hd.step(batch=batch, timeout_fired=fire,
                           apply_done=self.applied)
        self.hard.save(int(res["term"]), int(res["voted_term"]),
                       int(res["voted_for"]))
        was_leader = self._is_leader
        with self._lock:
            self._is_leader = int(res["role"]) == int(Role.LEADER)
        if res["became_leader"]:
            self.log.leader_elected(int(res["term"]))
        if res["hb_seen"] or self._is_leader:
            self.timer.beat()

        # fixed single fetch per iteration (SPMD-uniform)
        wd, wm = self.hd.fetch_local_window(self.applied)
        commit = int(res["commit"])
        n = min(commit - self.applied, self.cfg.window_slots)
        progressed = n > 0
        for j in range(max(n, 0)):
            etype = int(wm[j, M_TYPE])
            if etype in (int(EntryType.CONNECT), int(EntryType.SEND),
                         int(EntryType.CLOSE)):
                conn = int(wm[j, M_CONN])
                req = int(wm[j, M_REQID])
                ln = int(wm[j, M_LEN])
                payload = wd[j].astype("<i4").tobytes()[:ln]
                self.store.append(bytes([etype])
                                  + conn.to_bytes(4, "little") + payload)
                if (conn >> 24) != self.me:
                    if self.replay is not None:
                        self.replay.apply(etype, conn, payload)
                else:
                    with self._lock:
                        while self.inflight and self.inflight[0][1] <= req:
                            ev, _ = self.inflight.popleft()
                            ev.release(0)
        self.applied += max(n, 0)
        if progressed:
            if self.replay is not None:
                self.replay.drain_responses()
            self.store.sync()
        if not self._is_leader:
            with self._lock:
                while self.inflight:
                    ev, _ = self.inflight.popleft()
                    ev.release(-1)
        self.last = res
        return res

    def run_iterations(self, n: int, period: float = 0.0) -> None:
        """Run exactly ``n`` lock-step iterations (every host must use the
        same ``n`` — collective programs must match across hosts)."""
        import time
        for _ in range(n):
            self.iterate()
            if period:
                time.sleep(period)

    def close(self) -> None:
        self.proxy.close()
        if self.replay:
            self.replay.close()
        self.store.close()
        self.log.close()
