"""NodeDaemon — the per-host replica process for REAL multi-host clusters.

One of these runs on every host (the reference's per-machine app process
with ``interpose.so`` injected, ``benchmarks/run.sh:24-33``): it owns the
host's slice of the distributed consensus state (one replica on the local
chip), the proxy socket its interposed app connects to, the loopback replay
engine, the stable store, and the election timer.

Lock-step discipline: every loop iteration issues exactly ONE collective
program — the protocol step — so all hosts stay SPMD-consistent
regardless of how their local values differ; the committed-window fetch
is HOST-LOCAL (it reads only this replica's log shard) and runs only on
iterations where commit advanced. Hosts synchronize through the step's
collectives themselves (a host that runs ahead blocks in the next step
until peers arrive), exactly as the reference's followers synchronize
through RDMA completion semantics. A watchdog stamps a warning into the
replica log when one iteration stalls far beyond the cadence — the
symptom of a desynced or dead peer (the elastic supervisor reacts by
regenerating the world; see runtime/elastic.py).
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from rdma_paxos_tpu.config import (
    ClusterConfig, LogConfig, MAX_BURST_K, REBASE_STALL_STEPS,
    TimeoutConfig)
from rdma_paxos_tpu.consensus.log import EntryType, M_GIDX
from rdma_paxos_tpu.runtime import hostpath
from rdma_paxos_tpu.runtime.driver import conn_origin
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.obs import default as obs_default, trace as obs_trace
from rdma_paxos_tpu.obs.metrics import LATENCY_BUCKETS_S
from rdma_paxos_tpu.obs.spans import StepPhaseProfiler
from rdma_paxos_tpu.proxy.proxy import (
    PendingEvent, ProxyServer, ReplayEngine, spec_send_refused_dirty)
from rdma_paxos_tpu.proxy.stablestore import HardState, StableStore
from rdma_paxos_tpu.runtime.host import HostReplicaDriver
from rdma_paxos_tpu.runtime.timers import ElectionTimer
from rdma_paxos_tpu.utils.codec import fragment
from rdma_paxos_tpu.utils.debug import ReplicaLog


class NodeDaemon:
    def __init__(self, cfg: LogConfig, *, process_id: int,
                 num_processes: int, coordinator: str,
                 workdir: str, app_port: Optional[int] = None,
                 timeout_cfg: Optional[TimeoutConfig] = None,
                 group_size: Optional[int] = None, seed: int = 0,
                 host_id: Optional[int] = None,
                 genesis: Optional[dict] = None, gen: int = 0):
        self.cfg = cfg
        self.me = process_id
        # elastic generation number: namespaces this incarnation's
        # submit sequence (req stamps) and connection counters, so log
        # entries carried over from a PREVIOUS incarnation of this same
        # host can neither falsely ack this incarnation's inflight
        # events nor be mistaken for events this incarnation's app
        # already served (they must be REPLAYED into the rebuilt app)
        self.gen = gen
        # persistent host identity: stamps connection origins (conn_id >>
        # 24), so replay-vs-ack decisions survive slot renumbering across
        # elastic generations (process_id is the SLOT in this world; the
        # host_id is forever)
        self.host_id = process_id if host_id is None else host_id
        # RP_AUDIT=1 compiles the digest-chain step variant (must MATCH
        # on every host — the audit program is part of the collective
        # schedule) and records this replica's digest windows into a
        # local AuditLedger, dumped on a cadence to
        # <workdir>/replica<me>.audit.json; merge the per-host dumps
        # with `python -m rdma_paxos_tpu.obs.audit report ...` for the
        # cross-replica first-divergence verdict. The local ledger
        # alone already catches post-commit corruption of THIS host's
        # log memory (re-reported windows are self-checked).
        self._audit = os.environ.get("RP_AUDIT") == "1"
        self.hd = HostReplicaDriver(
            cfg, process_id=process_id, num_processes=num_processes,
            coordinator=coordinator, group_size=group_size,
            audit=self._audit)
        if genesis is not None:
            # elastic world rebuild: every member installs the identical
            # donor-derived row (collective — all daemons of the
            # generation pass a genesis or none do)
            self.hd.install_genesis(genesis)
        os.makedirs(workdir, exist_ok=True)
        self._lock = threading.Lock()
        self._is_leader = False
        self._submitq: List[Tuple[int, int, bytes, int]] = []
        self.inflight: collections.deque = collections.deque()
        self.submit_seq = 0   # per-incarnation; entries carry M_GEN so
                              # cross-incarnation req compares never happen
        self.applied = int(genesis["apply"]) if genesis is not None else 0
        self.needs_recovery = False   # force-pruned past our apply cursor
        # mis-speculation quarantine (same contract as ClusterDriver): a
        # SPECULATIVE app (shim HELLO flag) that consumed inputs failed
        # at deposition has diverged from the committed stream — the
        # store keeps persisting, the app gets nothing until rebuilt
        # (reset_app / generation bootstrap_from_store)
        self.app_dirty = False
        self.replicated_conns: set = set()
        self.passthrough_conns: set = set()
        self.sock_path = os.path.join(workdir, f"proxy{self.me}.sock")
        self.proxy = ProxyServer(self.sock_path, self.host_id,
                                 self._on_event,
                                 conn_ctr_start=(gen % 16) << 20)
        self.replay = (ReplayEngine("127.0.0.1", app_port)
                       if app_port else None)
        # stable files are keyed by the PERSISTENT host id: a restarted
        # host finds its own history regardless of which slot the new
        # generation assigns it
        self.store = StableStore(
            os.path.join(workdir, f"host{self.host_id}.db"))
        self.hard = HardState(
            os.path.join(workdir, f"host{self.host_id}.db.hs"))
        # a RESTARTED daemon restores its persisted election state so it
        # cannot double-vote in a term it voted in before the crash
        # (collective — every daemon calls this during init, with zeros
        # when no prior state exists)
        hs = self.hard.load()
        self.hd.restore_hardstate(*(hs if hs is not None else (0, 0, -1)))
        # per-host daemon: structured signals go to the process-global
        # obs facade (one daemon per process in deployment, so no
        # cross-instance mixing); the greppable log file is preserved
        self.obs = obs_default()
        # step-phase attribution for the lock-step loop (host encode /
        # device dispatch / apply / ack release). On this multi-host
        # path hd.step's output extraction already blocks on results,
        # so device_dispatch includes device time; RP_FENCE=1 opts into
        # the explicit fence anyway (useful on a directly-attached TPU
        # where extraction is lazy).
        self._phase_prof = StepPhaseProfiler(
            metrics=self.obs.metrics,
            fence=os.environ.get("RP_FENCE") == "1", replica=self.me)
        # total i32-rollover offset this incarnation applied: spans are
        # keyed by ABSOLUTE indices, invariant across rebases
        self._rebased_total = 0
        self.log = ReplicaLog(
            os.path.join(workdir, f"replica{self.me}.log"),
            replica=self.me, obs=self.obs)
        self.timer = ElectionTimer(timeout_cfg or TimeoutConfig(),
                                   seed=seed + process_id)
        if self._audit:
            from rdma_paxos_tpu.obs.audit import AuditLedger
            self.auditor = AuditLedger(num_processes, obs=self.obs)
            self._audit_path = os.path.join(
                workdir, f"replica{self.me}.audit.json")
        else:
            self.auditor = None
            self._audit_path = None
        self._audit_write_period = 5.0
        self._audit_last_write = float("-inf")
        # ops plane (the per-host half of the fleet console's view):
        # time-series retention sampled on the alert cadence —
        # persisted as replica<me>.series.jsonl, so merging N hosts'
        # series is a file concat — feeding the window-domain SLO
        # rules (rate_window / burn_rate), plus the per-host health
        # snapshot file the console merges across hosts
        from rdma_paxos_tpu.obs.health import HealthReporter
        from rdma_paxos_tpu.obs.series import TimeSeriesStore
        self.series = TimeSeriesStore(
            path=os.path.join(workdir,
                              f"replica{self.me}.series.jsonl"),
            source=f"replica{self.me}")
        self._health = HealthReporter(workdir, period=1.0)
        # SLO alert rules over the process-global registry, evaluated
        # on a cadence from the lock-step loop (obs/alerts.py)
        from rdma_paxos_tpu.obs.alerts import AlertEngine, default_rules
        self.alerts = AlertEngine(self.obs.metrics,
                                  rules=default_rules(),
                                  trace=self.obs.trace,
                                  series=self.series)
        self._alert_period = 1.0
        self._alert_last = float("-inf")
        self.iterations = 0       # the daemon's step-domain clock for
                                  # series points (one per iterate())
        # RP_METRICS_PORT: opt-in ops exporter (obs/export.py) —
        # /metrics /healthz /series /alerts on localhost; "0" binds
        # an ephemeral port (read it back from daemon.exporter.port).
        # Host-side only — the exporter never joins the collective
        # schedule, so hosts may disagree on it freely.
        self.exporter = None
        port = os.environ.get("RP_METRICS_PORT")
        if port is not None and port != "":
            from rdma_paxos_tpu.obs.export import OpsExporter
            self.exporter = OpsExporter(
                registry=self.obs.metrics, health_fn=self.health,
                alerts=self.alerts, series=self.series,
                port=int(port)).start()
        # RP_GOVERNOR=1: the adaptive-dispatch governor's multi-host
        # half (runtime/governor.py:HintGovernor). Its decision —
        # burst / serial step / bounded admission coalesce — derives
        # ONLY from the gathered burst_hint (the PR 6 k_needed
        # contract), so every host derives the same collective program
        # schedule with zero extra collectives. Like RP_BURST/RP_SCAN
        # the env must MATCH on every host. Content (what the leader
        # actually packs) stays local and never changes program shape.
        self.governor = None
        if os.environ.get("RP_GOVERNOR") == "1":
            from rdma_paxos_tpu.runtime.governor import HintGovernor
            self.governor = HintGovernor(cfg.batch_slots)
        # RP_CDC=1: change-data-capture export — every committed
        # client entry this daemon applies is appended to
        # <workdir>/replica<me>.cdc.jsonl in audit-chain coordinates
        # (term, absolute index) with the retained window digests, so
        # `python -m rdma_paxos_tpu.streams verify` can prove the
        # export against the replica's audit dump. Host-side only —
        # never joins the collective schedule.
        self.cdc = None
        if os.environ.get("RP_CDC") == "1":
            from rdma_paxos_tpu.streams.cdc import CDCWriter
            self.cdc = CDCWriter(
                os.path.join(workdir, f"replica{self.me}.cdc.jsonl"),
                auditor=self.auditor, obs=self.obs)
        self.last: Optional[Dict] = None
        self._rebase_warned = False
        # consecutive post-threshold iterations with the gathered
        # rebase_delta pinned at 0 (a heard-but-lagging row's low head
        # — the consensus/step.py liveness gap, ADVICE.md #3)
        self._rebase_stall_steps = 0
        self.rebase_stalled = 0

    # single multihost burst tier (see iterate) — identical on all
    # hosts; == config.MAX_BURST_K, which the rebase-headroom
    # validation in LogConfig accounts for
    BURST_K = MAX_BURST_K

    # consecutive zero-delta post-threshold iterations before the
    # stall is surfaced — shared with SimCluster
    # (config.REBASE_STALL_STEPS)
    REBASE_STALL_STEPS = REBASE_STALL_STEPS

    @property
    def scan_enabled(self) -> bool:
        """RP_SCAN=1 routes burst iterations through the K-window scan
        tier (``HostReplicaDriver.step_scan``): same fused protocol
        steps, but the readback is one consolidated scalar matrix plus
        this replica's replay window staged INSIDE the dispatch — the
        per-window ``fetch_local_window`` dispatches disappear. Like
        RP_BURST, the env must MATCH on every host (program schedule
        is collective); requires bursts (and their psum gate)."""
        return (self.burst_enabled
                and os.environ.get("RP_SCAN") == "1")

    @property
    def burst_enabled(self) -> bool:
        """Bursts amortize per-DISPATCH overhead — dominant on real TPU
        hosts (device launch / tunnel latency per program), negligible
        on the CPU multi-process test harness where per-collective
        cross-process syncs dominate and a fused K-step program costs
        the same collectives as K separate steps. Default: on for TPU,
        off for CPU; RP_BURST=1/0 overrides (must MATCH on all hosts —
        burst engagement is part of the collective program schedule).
        Measured on the 1-core CPU harness: 2000-SET drain 0.14 s
        without bursts vs 0.62 s with (the collective count is the
        bottleneck there, not dispatches).

        Bursts additionally REQUIRE full connectivity: K is agreed via
        the gathered burst_hint (a max over the leaders each replica
        heard), so an asymmetric peer_mask lets hosts disagree on K and
        call different collective programs — a distributed hang, not a
        clean failure. psum fan-out is the full-connectivity
        configuration (HostReplicaDriver.step refuses psum with any
        masked peer), so bursts are gated on it; under fanout='gather'
        (the partition-simulation mode) bursts stay off regardless of
        backend or RP_BURST."""
        if self.hd._fanout != "psum":
            return False
        env = os.environ.get("RP_BURST")
        if env is not None:
            return env == "1"
        import jax
        return jax.default_backend() == "tpu"

    def prewarm_burst(self) -> None:
        """COLLECTIVE: compile the burst program before serving (every
        host calls this at the same point, right after construction).
        Executes one empty K-step burst — harmless pre-election (no
        leader, nothing appends) — so the multi-second multi-process
        compile never lands inside a client-visible drain. No-op when
        bursts are disabled for this backend."""
        if self.scan_enabled:
            self.hd.step_scan(self.BURST_K, [], apply_done=self.applied,
                              gen=self.gen)
        elif self.burst_enabled:
            self.hd.step_burst(self.BURST_K, [], apply_done=self.applied,
                               gen=self.gen)

    # ------------------------------------------------------------------

    def _on_event(self, etype: int, conn_id: int, payload: bytes):
        with self._lock:
            if etype == int(EntryType.CONNECT):
                port = (int.from_bytes(payload[4:6], "big")
                        if len(payload) >= 6 else 0)
                if (self.replay is not None
                        and port in self.replay.local_ports):
                    self.passthrough_conns.add(conn_id)
                    return None
                if self.app_dirty:
                    # a dirty (mis-speculated) app serves nothing —
                    # not even stale local reads
                    return -1
                if not self._is_leader:
                    return None
                self.replicated_conns.add(conn_id)
                payload = b""
            elif conn_id in self.passthrough_conns:
                if etype == int(EntryType.CLOSE):
                    self.passthrough_conns.discard(conn_id)
                return None
            elif conn_id not in self.replicated_conns:
                return None
            elif self.app_dirty:
                self.replicated_conns.discard(conn_id)
                return -1
            elif not self._is_leader:
                if etype == int(EntryType.CLOSE):
                    self.replicated_conns.discard(conn_id)
                    return None
                # refusal strands bytes a speculative app already
                # executed: quarantine (shared policy with ClusterDriver
                # — proxy.spec_send_refused_dirty)
                if spec_send_refused_dirty(etype, conn_id,
                                           self.replicated_conns,
                                           self.proxy, self.app_dirty):
                    self.app_dirty = True
                    self.log.info_wtime(
                        "APP DIRTY: speculated SEND refused at intake "
                        "(conn %d)" % conn_id)
                return -1
            if etype == int(EntryType.CLOSE):
                self.replicated_conns.discard(conn_id)
            frags = (fragment(payload, self.cfg.slot_bytes)
                     if etype == int(EntryType.SEND) else [payload])
            ev = PendingEvent(EntryType(etype), conn_id, payload)
            for f in frags:
                self.submit_seq += 1
                self._submitq.append((etype, conn_id, f, self.submit_seq))
            self.inflight.append((ev, self.submit_seq))
            self.obs.spans.begin(conn_id, self.submit_seq, self.me)
            return ev

    # ------------------------------------------------------------------

    def iterate(self) -> Dict:
        """One lock-step loop iteration (call in unison on every host).

        BURST MODE: the previous step's gathered ``burst_hint`` (the
        leader's submit backlog, identical on every host under full
        connectivity) lets all hosts agree — with no extra collective —
        to fuse the next K protocol steps into ONE dispatch. K is
        derived ONLY from the gathered hint (local state like ring
        occupancy differs across hosts and would desync the collective
        program); the leader clamps the batch CONTENT it actually packs
        by its local capacity, which never changes program shape."""
        B = self.cfg.batch_slots
        prof = self._phase_prof
        prof.start("host_encode")
        hint = (int(self.last["burst_hint"])
                if self.last is not None
                and self.last.get("burst_hint") is not None else 0)
        if not self.burst_enabled:
            hint = 0
        k_needed = -(-hint // B) if hint > 0 else 0
        # RP_GOVERNOR=1: burst / step / coalesce from the gathered
        # hint ONLY — all hosts run the same pure decision function
        # over the same gathered sequence, so the collective program
        # schedule stays agreed (tests pin the agreement). "coalesce"
        # = one serial heartbeat iteration that HOLDS the local batch
        # (admission wait, bounded by the governor), so the next
        # burst ships a fuller window.
        hold_batch = False
        if self.governor is not None and self.burst_enabled:
            tier = self.governor.decide(hint)
            self.obs.metrics.inc("dispatch_tier", tier=(
                "burst%d" % self.BURST_K if tier == "burst" else
                "serial"))
            if tier == "coalesce":
                k_needed = 0
                hold_batch = True
                self.obs.metrics.inc("governor_coalesce_total",
                                     replica=self.me)
        # fused bursts are the DEFAULT e2e path: ANY gathered backlog
        # rides the one fixed-K burst program (shallow content padded
        # with empty steps), so per-dispatch overhead is amortized the
        # moment traffic exists — the single-step path serves only
        # idle heartbeats and election iterations. The decision derives
        # ONLY from the gathered hint, so every host agrees.
        scan_rows = None            # (wd, wm) staged by the scan tier
        if k_needed >= 1:
            # ONE fixed burst tier: every distinct K is a separate
            # multi-process shard_map compile (~seconds, and the
            # persistent cache does not serve these programs), so the
            # daemon compiles exactly one burst program — at boot, via
            # prewarm_burst — and pads shallow bursts with empty steps
            K = self.BURST_K
            with self._lock:
                # content clamp (local): ring free space so mid-burst
                # drops (which would reorder a connection's fragments
                # against later burst steps) cannot occur
                avail = ((self.cfg.n_slots - 1)
                         - (int(self.last["end"])
                            - int(self.last["head"])))
                take_n = min(len(self._submitq), max(avail, 0), K * B)
                take = self._submitq[:take_n]
                self._submitq = self._submitq[take_n:]
                qdepth = len(self._submitq)
            batches = [[(t, c, s, f) for (t, c, f, s)
                        in take[k * B:(k + 1) * B]] for k in range(K)]
            import time as _t
            _t0 = _t.monotonic()
            prof.stop("host_encode")
            prof.start("device_dispatch")
            if self.scan_enabled:
                # K-window scan tier: this replica's replay window
                # rides the dispatch — consumed by the apply loop
                # below before any standalone fetch
                res, scan_rows = self.hd.step_scan(
                    K, batches, apply_done=self.applied,
                    gen=self.gen, queue_depth=qdepth)
            else:
                res = self.hd.step_burst(K, batches,
                                         apply_done=self.applied,
                                         gen=self.gen,
                                         queue_depth=qdepth)
            prof.stop("device_dispatch")
            if os.environ.get("RP_BURST_DEBUG"):
                self.log.info_wtime(
                    "BURST K=%d take=%d dt=%.3fs" %
                    (K, len(take), _t.monotonic() - _t0))
            # every burst step carried the heartbeat; follower timers
            # are beaten below via hb_seen / leadership
        else:
            with self._lock:
                # a coalescing iteration holds the batch (admission
                # wait) — the heartbeat still ships, the entries ride
                # the next, fuller, burst
                take = [] if hold_batch else self._submitq[:B]
                if take:
                    self._submitq = self._submitq[B:]
                qdepth = len(self._submitq)
            # (etype, conn, req_seq, payload) rows for make_input
            batch = [(t, c, s, f) for (t, c, f, s) in take]

            fire = False
            if not self._is_leader and self.timer.expired():
                fire = True
                self.timer.beat()

            prof.stop("host_encode")
            prof.start("device_dispatch")
            res = self.hd.step(batch=batch, timeout_fired=fire,
                               apply_done=self.applied, gen=self.gen,
                               queue_depth=qdepth)
            prof.stop("device_dispatch")
            take_n = len(take)
        if take and int(res["role"]) == int(Role.LEADER):
            # ring-full shortfall: the appended set is a PREFIX of the
            # submitted rows — requeue the rest in order (a deposed
            # host's remainder is dropped; its events fail below)
            acc = int(res["accepted"]) if res["accepted"] is not None else 0
            spans = self.obs.spans
            if spans.open_count and acc > 0:
                # the accepted prefix landed at absolute indices
                # [end-acc, end): stamp each sampled span's (term,
                # index) correlation key — this host only observes its
                # own commit/apply frontiers (merges align cross-host)
                end_abs = int(res["end"]) + self._rebased_total
                term = int(res["term"])
                for i, (_t_, c, _f, s) in enumerate(take[:acc]):
                    spans.stamp_append(c, s, term, end_abs - acc + i,
                                       self.me, replicas=(self.me,))
            if acc < take_n:
                with self._lock:
                    self._submitq = take[acc:] + self._submitq
        if self.auditor is not None \
                and res.get("audit_digest") is not None:
            # BEFORE the rollover below: the emitted indices are raw,
            # consistent with the current _rebased_total
            self._ingest_audit(res)
        self.hard.save(int(res["term"]), int(res["voted_term"]),
                       int(res["voted_for"]))
        was_leader = self._is_leader
        with self._lock:
            self._is_leader = int(res["role"]) == int(Role.LEADER)
        if res["became_leader"]:
            self.log.leader_elected(int(res["term"]))
        if res["hb_seen"] or self._is_leader:
            self.timer.beat()

        # window drain only when commit advanced — the scan tier's
        # staged rows serve the first window with ZERO extra
        # dispatches; any remainder falls back to the host-local
        # fetch (reads our own log shard, loops independently): a
        # burst can commit up to K*batch_slots entries in one
        # dispatch, so drain window-by-window until caught up
        commit = int(res["commit"])
        progressed = False
        releases = []
        released_upto = -1
        prof.start("apply")

        def own_of(conns, gens):
            # "our own event" means THIS incarnation's (M_GEN column
            # matches our generation): its app thread already consumed
            # the bytes live — ack it. An entry from a previous
            # incarnation of this host is replayed like a remote one:
            # the rebuilt app has never seen it.
            return ((conn_origin(conns) == self.host_id)
                    & (gens == self.gen))

        while self.applied < commit and not self.needs_recovery:
            n = min(commit - self.applied, self.cfg.window_slots)
            if scan_rows is not None and scan_rows[0] is not None:
                wd, wm = scan_rows      # staged at this apply cursor
                scan_rows = None
            else:
                wd, wm = self.hd.fetch_local_window(self.applied)
            if int(wm[0, M_GIDX]) != self.applied:
                # our slot was recycled (forced pruning left this host
                # behind): recycled bytes must never reach the app —
                # stop applying and wait for recovery (the elastic
                # supervisor rebuilds us from a donor snapshot)
                self.needs_recovery = True
                self.log.info_wtime(
                    "PRUNED past apply cursor %d — snapshot "
                    "recovery required" % self.applied)
                break
            progressed = True
            # vectorized window decode + batched persist/replay/ack
            # (the shared host data plane): one framed-store append,
            # one replay plan, one ack-frontier pop per window
            batch = hostpath.decode_batch(wm, wd, n,
                                          self._rebased_total)
            if batch is not None:
                self.store.append_framed(batch.frames())
                if self.cdc is not None:
                    # RP_CDC=1: export the committed client entries in
                    # audit coordinates before acks release (an
                    # exported record is always also in the store)
                    self.cdc.write_batch(batch)
                own = own_of(batch.conns, batch.gens)
                own_max, ops = hostpath.replay_plan(
                    batch, own,
                    want_ops=(self.replay is not None
                              and not self.app_dirty))
                if own_max >= 0:
                    with self._lock:
                        while (self.inflight
                               and self.inflight[0][1] <= own_max):
                            ev, _ = self.inflight.popleft()
                            releases.append(ev)
                    released_upto = max(released_upto, own_max)
                if self.replay is not None and not self.app_dirty:
                    # dirty app: persist only — replay resumes after
                    # the app is rebuilt from the committed store
                    for etype, conn, payload in ops:
                        self.replay.apply(etype, conn, payload)
            self.applied += n
        prof.stop("apply")
        if progressed:
            if self.replay is not None:
                self.replay.drain_responses()
            # persist BEFORE acking (the reference's persist_new_entries
            # precedes apply/ack): a client ack implies the event is in
            # this host's stable store
            self.store.sync()
        # span frontiers BEFORE the ack marks (a span's commit/apply
        # precede its ack causally — recording them after would invert
        # the critical-path timestamps): this host observes only its
        # own replica's frontiers, in ABSOLUTE indices, and must run
        # before the rebase below (res offsets and _rebased_total are
        # both still pre-rollover here); cross-host correlation happens
        # at merge time via (term, index)
        spans = self.obs.spans
        if spans.open_count:
            spans.commit_advance(self.me, commit + self._rebased_total)
            spans.apply_advance(self.me,
                                self.applied + self._rebased_total)
        prof.start("ack_release")
        import time as _time
        _now = _time.perf_counter()
        for ev in releases:
            ev.release(0)
            self.obs.metrics.observe(
                "commit_latency_seconds", _now - ev.t0,
                buckets=LATENCY_BUCKETS_S, replica=self.me)
        if releases:
            self.obs.trace.record(obs_trace.PROXY_ACK_RELEASE,
                                  replica=self.me,
                                  count=len(releases))
            self.obs.spans.ack_release(self.me, released_upto)
        prof.stop("ack_release")
        if not self._is_leader:
            with self._lock:
                if (self.inflight and self.proxy.spec_mode
                        and not self.app_dirty):
                    # a speculative app already EXECUTED the inputs being
                    # failed: quarantine until rebuilt (reset_app or the
                    # next generation's bootstrap_from_store)
                    self.app_dirty = True
                    self.log.info_wtime(
                        "APP DIRTY: %d speculated events failed at "
                        "deposition" % len(self.inflight))
                n_failed = len(self.inflight)
                while self.inflight:
                    ev, _ = self.inflight.popleft()
                    ev.release(-1)
                if n_failed:
                    # deposed with blocked waiters: their spans must
                    # close (failover), never leak
                    self.obs.spans.fail_open(self.me)
        # coordinated i32-offset rollover: the gathered rebase_delta is
        # identical on every host under full connectivity (psum fan-out
        # — the only configuration this daemon bursts or rebases in), so
        # every host applies the same subtraction in the same iteration.
        # The rebase program itself is elementwise (no collectives), so
        # no cross-host ordering hazard exists even in principle.
        rd = res.get("rebase_delta")
        if rd is not None and int(rd) > 0:
            if self.hd._fanout == "psum":
                delta = int(rd)
                self.hd.rebase(delta)
                self.applied -= delta
                self._rebased_total += delta
                self._rebase_stall_steps = 0     # re-arm stall detect
                self.obs.metrics.inc("rebases_total")
                self.obs.trace.record(obs_trace.REBASE_APPLIED,
                                      replica=self.me, delta=delta)
                self.log.info_wtime(
                    "REBASE: offsets dropped by %d (i32 rollover)"
                    % delta)
            elif not self._rebase_warned:
                # under gather fan-out the gathered delta is NOT
                # guaranteed identical across hosts (heard masks can
                # differ), so applying it could diverge offsets — but
                # silently discarding it would let the i32 ceiling
                # arrive unannounced. Warn loudly, once.
                self._rebase_warned = True
                self.log.info_wtime(
                    "WARNING: rebase_delta=%d ignored (fanout=%r is "
                    "not full-connectivity); offsets are approaching "
                    "the i32 ceiling with no rollover possible"
                    % (int(rd), self.hd._fanout))
        elif int(res["end"]) >= self.cfg.rebase_threshold:
            # end crossed the threshold but the gathered delta stayed 0
            # — a heard-but-lagging row (stalled learner) is pinning
            # the min head, and the rollover will never fire on its
            # own. Surface it so operators see the i32 ceiling
            # approaching in the psum path too (ADVICE.md #3).
            self._rebase_stall_steps += 1
            if self._rebase_stall_steps >= self.REBASE_STALL_STEPS:
                self.rebase_stalled += 1
                self.obs.metrics.inc("rebase_stalled")
                if self._rebase_stall_steps == self.REBASE_STALL_STEPS:
                    self.obs.trace.record(
                        obs_trace.REBASE_STALLED, replica=self.me,
                        end=int(res["end"]),
                        threshold=self.cfg.rebase_threshold,
                        steps=self._rebase_stall_steps)
                    self.log.info_wtime(
                        "REBASE STALLED: end=%d crossed threshold=%d "
                        "but rebase_delta stayed 0 for %d steps — a "
                        "lagging heard row is pinning the min head; "
                        "the i32 ceiling is approaching"
                        % (int(res["end"]), self.cfg.rebase_threshold,
                           self._rebase_stall_steps))
        # per-iteration host gauges (role/term/progress/headroom): the
        # structured twin of the log file, exported with every snapshot
        self.obs.metrics.set("replica_role", int(res["role"]),
                             replica=self.me)
        self.obs.metrics.set("replica_term", int(res["term"]),
                             replica=self.me)
        self.obs.metrics.set("commit_index", commit, replica=self.me)
        self.obs.metrics.set("rebase_headroom",
                             self.cfg.rebase_threshold
                             - int(res["end"]), replica=self.me)
        self.obs.metrics.set("cluster_leader", int(res["leader_id"]))
        with self._lock:
            self.obs.metrics.set("inflight_waiters", len(self.inflight),
                                 replica=self.me)
        self.iterations += 1
        self.last = res      # before the cadence block: health()
                             # must read THIS iteration's outputs
        import time as _tmono
        now = _tmono.monotonic()
        if now - self._alert_last >= self._alert_period:
            self._alert_last = now
            # series sampling shares the snapshot with the rule pass
            # (the drivers' cadence contract), then the per-host
            # health file refreshes — the surface the fleet console
            # and the elastic supervisor watch from outside
            snap = self.obs.metrics.snapshot()
            self.series.sample(snap, step=self.iterations)
            self.alerts.evaluate(snap=snap)
            try:
                self._health.write({self.me: self.health()})
            except OSError:
                pass     # observability I/O never kills the loop
        if (self._audit_path is not None and self.auditor is not None
                and now - self._audit_last_write
                >= self._audit_write_period):
            self._audit_last_write = now
            try:
                self.auditor.write_json(self._audit_path)
            except OSError:
                pass     # evidence I/O must never kill the data path
        return res

    def _ingest_audit(self, res: Dict) -> None:
        """Record this replica's digest windows (single step or every
        fused burst step) into the local ledger in ABSOLUTE indices."""
        led = self.auditor
        W = self.cfg.window_slots
        reb = self._rebased_total
        dig = res["audit_digest"]
        if dig.ndim == 1:
            rows = [(int(res["audit_start"]), int(res["commit"]),
                     dig, res["audit_term"])]
        else:                              # burst: [K, W] windows
            rows = [(int(res["audit_start"][k]),
                     int(res["audit_commit"][k]), dig[k],
                     res["audit_term"][k])
                    for k in range(dig.shape[0])]
        for start, commit, d, t in rows:
            n = commit - start
            if n <= 0:
                continue
            off = start - (commit - W)
            led.record_window(self.me, start + reb, d[off:off + n],
                              t[off:off + n], commit + reb)

    def health(self) -> Dict:
        """THIS host's replica health snapshot (the obs.health
        per-replica schema plus daemon extras) — written to
        ``replica<me>.health.json`` on the reporter cadence, served
        at ``/healthz`` when RP_METRICS_PORT is set, and merged
        across hosts by the fleet console (N daemon files = one
        cluster seen from N sides)."""
        from rdma_paxos_tpu.obs.health import make_snapshot
        res = getattr(self, "last", None)
        with self._lock:
            inflight = len(self.inflight)
        return make_snapshot(
            replica=self.me,
            host_id=self.host_id,
            gen=self.gen,
            role=(int(res["role"]) if res is not None else -1),
            term=(int(res["term"]) if res is not None else 0),
            leader_id=(int(res["leader_id"]) if res is not None
                       else -1),
            commit=(int(res["commit"]) if res is not None else 0),
            apply=self.applied,
            end=(int(res["end"]) if res is not None else 0),
            head=(int(res["head"]) if res is not None else 0),
            log_headroom=(self.cfg.rebase_threshold
                          - (int(res["end"]) if res is not None
                             else 0)),
            inflight=inflight,
            app_dirty=self.app_dirty,
            needs_recovery=self.needs_recovery,
            rebase_stalled=self.rebase_stalled,
            store=self.store.stats(),
            alerts=self.alerts.state(),
            audit=(self.auditor.summary()
                   if self.auditor is not None else None),
        )

    def bootstrap_from_store(self) -> None:
        """Rebuild a FRESH local app instance by replaying the stable
        store's full event history into it. Call once at generation
        start, before the first ``iterate`` — the supervisor restarts
        the app, this fills it."""
        from rdma_paxos_tpu.proxy.proxy import replay_store_into
        replay_store_into(self.store, self.replay)
        self.app_dirty = False

    def reset_app(self, app_port: Optional[int] = None) -> None:
        """Exit mis-speculation quarantine: the supervisor restarted the
        app FRESH; rebuild it from this host's own committed store and
        resume live replay."""
        if self.replay is not None:
            self.replay.close()
            self.replay = ReplayEngine(
                "127.0.0.1",
                app_port if app_port is not None else self.replay.addr[1])
        self.bootstrap_from_store()
        self.log.info_wtime("APP RESET: rebuilt from committed store")

    def dump_row(self) -> dict:
        """THIS replica's full consensus state row (host numpy) — what
        the supervisor persists at generation exit and serves to the next
        generation's members if elected donor."""
        return self.hd.export_local_row()

    def meta(self, row: Optional[dict] = None) -> Dict[str, int]:
        """Donor-election metadata: Raft's up-to-date ordering key plus
        progress offsets (the controller picks the donor by
        ``(last_log_term, end)`` — Leader Completeness). Pass a
        pre-exported ``row`` to avoid a second device read."""
        from rdma_paxos_tpu.consensus.log import M_TERM
        if row is None:
            row = self.dump_row()
        end = int(row["end"])
        lterm = 0
        if end > 0:
            slot = (end - 1) & (self.cfg.n_slots - 1)
            lterm = int(row["log_buf"][slot,
                                       self.cfg.slot_words + M_TERM])
        # donor eligibility: a usable recovery point must PHYSICALLY
        # hold every entry from its host apply cursor onward (a
        # force-pruned laggard does not — installing its row would wedge
        # the whole new generation at the first M_GIDX check)
        usable = int(not self.needs_recovery
                     and self.applied >= int(row["head"])
                     and self.applied >= end - self.cfg.n_slots)
        return dict(term=int(row["term"]), last_log_term=lterm,
                    end=end, commit=int(row["commit"]),
                    apply=int(row["apply"]), applied=self.applied,
                    leader=int(self._is_leader), usable=usable)

    def run_iterations(self, n: int, period: float = 0.0,
                       watchdog_secs: float = 60.0) -> None:
        """Run exactly ``n`` lock-step iterations (every host must use the
        same ``n`` — collective programs must match across hosts). An
        iteration blocked in the step's collectives for more than
        ``watchdog_secs`` (compiles excluded by using the post-first-
        iteration baseline) logs a desync warning."""
        import time
        for i in range(n):
            t0 = time.monotonic()
            self.iterate()
            dt = time.monotonic() - t0
            if i > 0 and dt > watchdog_secs:
                self.log.info_wtime(
                    f"WATCHDOG: iteration blocked {dt:.1f}s — peer "
                    "desync or death suspected")
            if period:
                time.sleep(period)

    def close(self) -> None:
        if self.auditor is not None and self._audit_path is not None:
            try:
                self.auditor.write_json(self._audit_path)
            except OSError:
                pass
        if self.exporter is not None:
            self.exporter.close()
        try:
            # final health snapshot — the post-exit state the console
            # (and a postmortem bundle) reads after the process is gone
            self._health.write({self.me: self.health()})
        except OSError:
            pass
        if self.cdc is not None:
            self.cdc.close()
        self.series.close()
        self.proxy.close()
        if self.replay:
            self.replay.close()
        self.store.close()
        self.log.close()
