"""Failure-detection timers — host control plane.

The reference detects leader failure by followers checking a heartbeat SID
slot on a timer (``hb_receive_cb``, ``dare_server.c:822-922``) with an
**adaptive** election timeout that grows when it observes false positives
(``to_adjust_cb`` ``:763-817``: the timeout is raised until the false-
positive rate over recent trials is negligible). Randomization within
[low, high] desynchronizes simultaneous candidacies (classic Raft; the
reference draws random election timeouts the same way).
"""

from __future__ import annotations

import random
import time
from typing import Optional

from rdma_paxos_tpu.config import TimeoutConfig


class ElectionTimer:
    """Per-replica election timer with adaptive widening.

    ``beat()`` on every observed heartbeat; ``expired()`` polls; a timeout
    that turns out to be a false positive (the leader was alive — we saw
    its heartbeat again within the old term) should be reported via
    ``false_positive()``, which widens the low bound multiplicatively,
    mirroring the reference's grow-until-quiet adjustment."""

    def __init__(self, cfg: TimeoutConfig, seed: Optional[int] = None,
                 clock=time.monotonic):
        self.cfg = cfg
        self.low = cfg.elec_timeout_low
        self.high = cfg.elec_timeout_high
        self._rng = random.Random(seed)
        self._clock = clock
        self._deadline = 0.0
        self.beat()

    def _draw(self) -> float:
        return self._rng.uniform(self.low, self.high)

    def beat(self) -> None:
        self._deadline = self._clock() + self._draw()

    def expired(self) -> bool:
        return self._clock() >= self._deadline

    def remaining(self) -> float:
        """Seconds until this timer would fire (0.0 when already
        expired) — the idle-quiescence margin: a parked poll loop must
        wake and heartbeat well before any follower timer fires."""
        return max(0.0, self._deadline - self._clock())

    def false_positive(self) -> None:
        self.low = min(self.low * 1.5, self.high)
        self.beat()


class GroupStepTimer:
    """Per-group jittered election timer in the STEP domain — the
    production sharded driver's replacement for wall-clock
    ``ElectionTimer`` choreography (and for explicit ``place_leaders``
    timeout scripting).

    The driver polls in logical steps, so the timer counts polling
    iterations, not seconds: a leaderless group fires after a jittered
    ``[lo, hi]`` step period, re-drawn after every firing (randomized-
    timeout desynchronization, the :class:`ElectionTimer` analog with
    steps for seconds — the same domain as the chaos harness's
    ``StepTimerModel``). Seeding is per ``(seed, group)`` through the
    string-seeded RNG (sha512, PYTHONHASHSEED-independent), so a chaos
    replay that replays the same step sequence redraws the identical
    periods — election timing is bit-reproducible where a wall-clock
    timer would race the scheduler."""

    def __init__(self, group: int, seed: int = 0, lo: int = 6,
                 hi: int = 12):
        if not 1 <= lo <= hi:
            raise ValueError(f"need 1 <= lo <= hi, got [{lo}, {hi}]")
        self.group = int(group)
        self.lo, self.hi = int(lo), int(hi)
        self._rng = random.Random(f"group-timer:{seed}:{group}")
        self._since = 0
        self._period = self._rng.randint(self.lo, self.hi)

    def beat(self) -> None:
        """A heartbeat (the group is led) — reset the countdown."""
        self._since = 0

    def tick(self) -> bool:
        """Advance one polling step; True when the timer fires (and
        the next period is re-jittered)."""
        self._since += 1
        if self._since >= self._period:
            self._since = 0
            self._period = self._rng.randint(self.lo, self.hi)
            return True
        return False


class Pacer:
    """Fixed-period pacing for the host polling loop (the libev timer
    cadence: hb_period for leaders doubles as the step cadence here,
    since every step carries the heartbeat)."""

    def __init__(self, period: float, clock=time.monotonic,
                 sleep=time.sleep):
        self.period = period
        self._clock = clock
        self._sleep = sleep
        self._next = clock()

    def wait(self) -> None:
        now = self._clock()
        if now < self._next:
            self._sleep(self._next - now)
        self._next = max(self._next + self.period, now)
