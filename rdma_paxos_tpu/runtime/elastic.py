"""Elastic multi-host deployment — generation-based world rebuild.

The reference's elasticity is a side-channel story: a joiner multicasts
JOIN into the running group over UD/IB-multicast, the leader allocates a
slot or up-sizes, and the joiner snapshot-recovers over RDMA
(``handle_server_join_request`` ``dare_ibv_ud.c:972-1068``;
``rc_recover_sm``/``rc_recover_log`` ``dare_ibv_rc.c:603-856``). RDMA QPs
can be built to a new peer while the group keeps running.

An XLA world cannot: the mesh, the collectives, and the process set are
compiled in. The TPU-native elasticity design therefore moves membership
change OUT of the data plane and into a DCN control plane, as a sequence
of **generations**:

* A generation is a fixed member set running the ordinary lock-step
  :class:`~rdma_paxos_tpu.runtime.node.NodeDaemon` loop in a dedicated
  worker process (its own ``jax.distributed`` world, its own coordinator
  port).
* A :class:`GroupController` (the IB-multicast-group analog) tracks
  registrations and cuts a new generation whenever the member set needs
  to change — a host died (its worker stops posting round barriers /
  survivors report the collective failure), left, or (re)joined.
* On a cut, every member of the new generation installs an identical
  GENESIS state derived from the **donor** — the most up-to-date
  survivor by Raft's election ordering ``(last_log_term, end)``. With the
  controller refusing to cut unless the survivors include a majority of
  the previous generation, the donor's log contains every committed
  entry (Leader Completeness), so acked client writes survive any
  tolerated failure. The donor's uncommitted suffix carries over and is
  committed or truncated by the new generation's first leader, exactly
  like a Raft restart.
* The joiner (and, uniformly, every member) adopts the donor's stable
  store and rebuilds its app instance by replaying it — the
  ``proxy_apply_db_snapshot`` analog — so a restarted host serves the
  full replicated history the moment its generation starts.

Workers cannot rely on crash handlers: the JAX coordination-service
client LOG(FATAL)s the whole process the instant it learns a peer died,
racing (and often beating) the catchable collective error. So recovery
points are written BEFORE failures, not at them: after every completed
iteration a small (state row, meta + live-store length) pair is renamed
into place (atomic against process death), and :func:`best_recovery`
pairs it with the live store trimmed to the recorded length — the
freshest recovery point, containing every write the member acked, is
never more than one iteration old regardless of how the process dies. A
durable fsynced full triple is additionally written at every round
barrier (the power-loss tier). A member hard-killed outright counts as a
FAILED member: acked-write survival needs only a majority of SURVIVING
members, whose recovery points carry every committed entry. The
supervisor (this module's :class:`ElasticSupervisor`) never runs JAX
itself and survives any worker death; it freezes the recovery point it
offers (and serves to fetches) at registration time, so every member of
a cut installs exactly the state the donor election ranked.

Wire protocol: newline-delimited JSON over short-lived TCP connections;
binary blobs ride length-prefixed after the JSON header.
"""

from __future__ import annotations

import io
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from rdma_paxos_tpu.obs import trace as obs_trace
from rdma_paxos_tpu.obs.metrics import default_registry
from rdma_paxos_tpu.obs.trace import default_ring


# ---------------------------------------------------------------------------
# framing helpers
# ---------------------------------------------------------------------------

def _send_msg(sock: socket.socket, obj: dict,
              blobs: Tuple[bytes, ...] = ()) -> None:
    head = json.dumps(obj).encode() + b"\n"
    sock.sendall(struct.pack("<I", len(head)) + head)
    sock.sendall(struct.pack("<I", len(blobs)))
    for b in blobs:
        sock.sendall(struct.pack("<Q", len(b)))
        sock.sendall(b)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)                # linear even for large snapshots
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], min(n - got, 1 << 20))
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> Tuple[dict, List[bytes]]:
    (hlen,) = struct.unpack("<I", _recv_exact(sock, 4))
    obj = json.loads(_recv_exact(sock, hlen))
    (nblobs,) = struct.unpack("<I", _recv_exact(sock, 4))
    blobs = []
    for _ in range(nblobs):
        (blen,) = struct.unpack("<Q", _recv_exact(sock, 8))
        blobs.append(_recv_exact(sock, blen))
    return obj, blobs


def call(addr: str, obj: dict, blobs: Tuple[bytes, ...] = (),
         timeout: float = 60.0) -> Tuple[dict, List[bytes]]:
    """One request/response round trip to ``host:port``."""
    host, port = addr.rsplit(":", 1)
    with socket.create_connection((host, int(port)),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        _send_msg(s, obj, blobs)
        return _recv_msg(s)


def _row_to_npz(row: dict) -> bytes:
    bio = io.BytesIO()
    np.savez(bio, **row)
    return bio.getvalue()


def _npz_to_row(blob: bytes) -> dict:
    with np.load(io.BytesIO(blob)) as z:
        return {k: z[k] for k in z.files}


# ---------------------------------------------------------------------------
# dump files (the worker's recovery points)
# ---------------------------------------------------------------------------

def dump_path(workdir: str, host_id: int) -> str:
    return os.path.join(workdir, f"dump_h{host_id}.bin")


def write_dump(workdir: str, host_id: int, row: dict, store_blob: bytes,
               meta: dict) -> None:
    """Atomically persist a consistent (state row, store, meta) triple as
    ONE file — a crash can only ever leave the previous complete triple,
    never a mixed pair."""
    from rdma_paxos_tpu.proxy.stablestore import atomic_write
    row_npz = _row_to_npz(row)
    head = json.dumps(meta).encode()
    atomic_write(
        dump_path(workdir, host_id),
        struct.pack("<I", len(head)) + head
        + struct.pack("<Q", len(row_npz)) + row_npz
        + struct.pack("<Q", len(store_blob)) + store_blob)


def read_dump(workdir: str, host_id: int
              ) -> Optional[Tuple[dict, bytes, dict]]:
    try:
        with open(dump_path(workdir, host_id), "rb") as f:
            (hlen,) = struct.unpack("<I", f.read(4))
            meta = json.loads(f.read(hlen))
            (rlen,) = struct.unpack("<Q", f.read(8))
            row = _npz_to_row(f.read(rlen))
            (slen,) = struct.unpack("<Q", f.read(8))
            store = f.read(slen)
            if len(store) != slen:
                return None
    except (OSError, json.JSONDecodeError, ValueError, struct.error):
        return None
    return row, store, meta


# --- per-iteration recovery points (row + meta only) -----------------------
#
# Workers can be killed INSTANTLY and un-catchably — the JAX
# coordination-service client LOG(FATAL)s the whole process the moment it
# learns a peer died, racing (and often beating) the catchable collective
# error. No crash handler can be relied on, so after EVERY iteration the
# worker persists a small (state row, meta) pair by atomic rename
# (durable=False: safe against process death, which is the threat here).
# The meta records the live store's record count at that moment; recovery
# reconstructs the matching store blob by trimming the live store file —
# so the freshest recovery point is never more than one iteration old,
# abort or no abort.

def rowdump_path(workdir: str, host_id: int) -> str:
    return os.path.join(workdir, f"rowdump_h{host_id}.bin")


def write_rowdump(workdir: str, host_id: int, row: dict,
                  meta: dict) -> None:
    from rdma_paxos_tpu.proxy.stablestore import atomic_write
    row_npz = _row_to_npz(row)
    head = json.dumps(meta).encode()
    atomic_write(rowdump_path(workdir, host_id),
                 struct.pack("<I", len(head)) + head
                 + struct.pack("<Q", len(row_npz)) + row_npz,
                 durable=False)


def read_rowdump(workdir: str, host_id: int
                 ) -> Optional[Tuple[dict, dict]]:
    try:
        with open(rowdump_path(workdir, host_id), "rb") as f:
            (hlen,) = struct.unpack("<I", f.read(4))
            meta = json.loads(f.read(hlen))
            (rlen,) = struct.unpack("<Q", f.read(8))
            blob = f.read(rlen)
            if len(blob) != rlen:
                return None
            row = _npz_to_row(blob)
    except (OSError, json.JSONDecodeError, ValueError, struct.error):
        return None
    return row, meta


def best_recovery(workdir: str, host_id: int
                  ) -> Optional[Tuple[dict, bytes, dict]]:
    """The freshest consistent (row, store blob, meta) recovery point:
    the per-iteration rowdump (paired with the live store trimmed to its
    recorded length) when it is newer than the last barrier dump, else
    the barrier dump."""
    from rdma_paxos_tpu.proxy.stablestore import trimmed_dump

    def freshness(m: dict):
        # generations strictly order recovery points: a later world's
        # genesis can legitimately START with a lower applied offset
        # than an earlier world reached, and regressing across worlds
        # would lose the later world's acked writes
        return (int(m.get("gen", 0)), int(m.get("applied", -1)))

    barrier = read_dump(workdir, host_id)
    rd = read_rowdump(workdir, host_id)
    if rd is not None:
        row, meta = rd
        if barrier is None or freshness(meta) >= freshness(barrier[2]):
            store_path = os.path.join(workdir, f"host{host_id}.db")
            n = int(meta.get("store_len", 0))
            try:
                blob = (trimmed_dump(store_path, n)
                        if os.path.exists(store_path) else b"")
            except OSError:
                blob = None
            if blob is not None:
                return row, blob, meta
    return barrier


# ---------------------------------------------------------------------------
# GroupController — the DCN rendezvous / membership service
# ---------------------------------------------------------------------------

class GroupController:
    """Membership + generation service (the IB multicast group +
    ``handle_server_join_request`` control role, re-homed to DCN).

    Ops (JSON over :func:`call`):

    * ``register`` — a supervisor offers its host for the next
      generation (with its latest dump meta for donor election).
    * ``poll`` — fetch the current generation spec.
    * ``round`` — worker round barrier; doubles as the generation-change
      signal (``ok=0`` tells workers to exit for a rebuild).
    * ``fail`` — a supervisor reports its worker died on a collective
      error; the generation is broken and will be re-cut.
    * ``leave`` — graceful departure.
    """

    def __init__(self, port: int = 0, *, expect: int,
                 settle: float = 0.7, barrier_timeout: float = 120.0):
        # barrier_timeout bounds how long one member may lag the others
        # at a round barrier before the generation is declared broken; it
        # must comfortably exceed a generation's FIRST round, which
        # includes cold XLA compiles of the whole protocol step.
        self.expect = expect
        self.settle = settle
        self.barrier_timeout = barrier_timeout
        self._lock = threading.Condition()
        # host -> {"addr", "meta"}: supervisors waiting for the next cut
        self._reg: Dict[int, dict] = {}
        self._reg_changed = time.monotonic()
        self._gen = 0
        self._spec: Optional[dict] = None      # active generation spec
        self._prev_members: List[int] = []
        self._regen_wanted = False
        self._barriers: Dict[Tuple[int, int], set] = {}
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", port))
        self._srv.listen(32)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        # the CUTTER owns the settle deadline: cuts fire the moment the
        # expected membership is complete (event-driven) or when the
        # settle window elapses after the last registration — never
        # dependent on the timing of the NEXT incoming RPC (the old
        # behavior re-evaluated only inside request handlers, making
        # cut latency a function of worker poll cadence: the elastic
        # suite's flake locus)
        self._cutter = threading.Thread(target=self._cut_loop, daemon=True)
        self._cutter.start()

    # ------------------------------------------------------------------

    def _break(self, reason: str) -> None:
        """Break the RUNNING generation (workers exit at their next
        round barrier) and log why. No-op logging-wise when no
        generation is active — there is nothing to break, only pending
        registrations to re-evaluate. Caller holds the lock."""
        if self._spec is not None:
            print(f"controller: gen {self._gen} break — {reason}",
                  flush=True)
            # structured twin of the print: the elastic control plane's
            # churn signal (breaks per wall-clock = regen storm alarm)
            default_registry().inc("elastic_generation_breaks_total")
            default_ring().record(obs_trace.GENERATION_BREAK,
                                  gen=self._gen, reason=reason)
        self._regen_wanted = True
        self._lock.notify_all()

    def _maybe_cut(self) -> None:
        """Cut a new generation if the pending set is stable + quorate.
        Caller holds the lock."""
        if self._spec is not None and not self._regen_wanted:
            return
        hosts = sorted(self._reg)
        if not hosts:
            return

        def _donor_eligible(h: int) -> bool:
            m = self._reg[h].get("meta")
            return bool(m) and bool(int(m.get("usable", 1)))

        group_has_history = any(self._reg[h].get("meta") for h in hosts)
        if self._prev_members:
            # survivors must include a majority of the previous world,
            # else the donor cannot be proven complete (Raft overlap)
            maj = len(self._prev_members) // 2 + 1
            prev = set(self._prev_members)
            if len(prev.intersection(hosts)) < maj:
                return
            # When the group HAS history, only DONOR-ELIGIBLE survivors
            # count toward that majority: the donor election below skips
            # force-pruned laggards (usable=0) and meta-less
            # registrations, so letting them justify the cut could
            # elect a donor missing a committed entry whose only
            # surviving holder is the unusable host (commit acked by
            # leader+wedged follower, leader dies, third follower
            # lags) — the cut must wait for a provably complete donor
            # set. When NO survivor has any meta (every disk was lost),
            # there is nothing recoverable anywhere: fall through to
            # the fresh-world cut below rather than deadlock.
            if group_has_history:
                eligible = [h for h in hosts
                            if h in prev and _donor_eligible(h)]
                if len(eligible) < maj:
                    return
        elif len(hosts) < self.expect:
            return
        # event-driven cut: a REBUILD with every previous member back
        # has nobody to settle for — cut immediately. Fresh worlds and
        # partial-survivor rebuilds wait out the settle window (batching
        # near-simultaneous registrations — a fresh boot of MORE than
        # `expect` hosts must not cut at the expect-th registration and
        # immediately churn on the next newcomer); the cutter thread
        # owns that deadline.
        full = bool(self._prev_members) and (
            set(self._prev_members) <= set(hosts))
        if (not full
                and time.monotonic() - self._reg_changed < self.settle):
            return
        # the generation's workers still running must have been told to
        # exit before their hosts re-registered; hosts in _reg are idle
        self._gen += 1
        donor, donor_key = -1, (-1, -1)
        term_base = 0
        has_meta = False
        for h in hosts:
            m = self._reg[h].get("meta")
            if not m:
                continue
            has_meta = True
            term_base = max(term_base, int(m.get("term", 0)))
            if not m.get("usable", 1):
                # a force-pruned laggard's log no longer holds its own
                # apply cursor: installing it would wedge the generation
                continue
            key = (int(m.get("last_log_term", 0)), int(m.get("end", 0)))
            if key > donor_key:
                donor, donor_key = h, key
        if has_meta and donor < 0:
            # the group HAS history but no member can donate it (every
            # dump is unusable): cutting a fresh world here would
            # silently discard committed state — refuse and wait for
            # operator intervention or a usable registration, exactly
            # like the majority-overlap guard above
            return
        members = [{"host": h, "addr": self._reg[h]["addr"]}
                   for h in hosts]
        coord_host = self._reg[hosts[0]]["addr"].rsplit(":", 1)[0]
        self._spec = {
            "gen": self._gen,
            "members": members,
            "coordinator": f"{coord_host}:{self.port + 100 + self._gen}",
            "donor": donor,
            "donor_addr": (self._reg[donor]["addr"] if donor >= 0
                           else ""),
            "term_base": term_base,
            "epoch": self._gen,
            # workers derive their round-RPC client timeout from this,
            # so raising the controller's barrier budget (slow cold
            # compiles) can never make healthy workers time out first
            "barrier_timeout": self.barrier_timeout,
        }
        self._prev_members = hosts
        self._reg.clear()
        self._regen_wanted = False
        self._barriers.clear()
        default_registry().inc("elastic_generation_cuts_total")
        default_registry().set("elastic_generation", self._gen)
        default_ring().record(obs_trace.GENERATION_CUT, gen=self._gen,
                              members=hosts, donor=donor,
                              term_base=term_base)
        self._lock.notify_all()

    def _cut_loop(self) -> None:
        """Re-evaluate pending cuts when the settle deadline passes —
        independent of RPC arrival timing."""
        with self._lock:
            while not self._stop.is_set():
                before = self._gen
                self._maybe_cut()
                if self._gen != before:
                    continue
                if self._reg and (self._spec is None
                                  or self._regen_wanted):
                    left = (self._reg_changed + self.settle
                            - time.monotonic())
                    # settle deadline already passed but the cut is
                    # blocked on something else (majority overlap /
                    # donor eligibility): no point busy-waking — only a
                    # registration (which notifies) can unblock it
                    self._lock.wait(timeout=left if left > 0 else 1.0)
                else:
                    self._lock.wait(timeout=1.0)

    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        if op == "round":
            return self._round(req)
        with self._lock:
            if op == "register":
                h = int(req["host"])
                if not 0 <= h < 128:
                    # never admit an id the proxy layer cannot encode:
                    # a generation containing it would crash on spawn
                    return {"error": f"host id {h} out of range 0..127"}
                self._reg[h] = {"addr": req["addr"],
                                "meta": req.get("meta")}
                self._reg_changed = time.monotonic()
                if (self._spec is not None
                        and h not in [m["host"]
                                      for m in self._spec["members"]]):
                    # a newcomer wants in
                    self._break(f"newcomer h{h} registered")
                self._maybe_cut()
                return {"gen": self._gen}
            if op == "poll":
                self._maybe_cut()
                h = int(req["host"])
                if (self._spec is not None
                        and h in [m["host"]
                                  for m in self._spec["members"]]):
                    return dict(self._spec, ok=1)
                return {"ok": 0, "gen": self._gen, "pending": True}
            if op in ("fail", "leave"):
                h = int(req["host"])
                self._break(f"{op} from h{h}")
                if op == "leave":
                    self._reg.pop(h, None)
                return {"ok": 1, "gen": self._gen}
            return {"error": f"unknown op {op!r}"}

    def _round(self, req: dict) -> dict:
        g, r, h = int(req["gen"]), int(req["round"]), int(req["host"])
        deadline = time.monotonic() + self.barrier_timeout
        with self._lock:
            if self._spec is None or g != self._spec["gen"]:
                return {"ok": 0, "gen": self._gen}
            members = {m["host"] for m in self._spec["members"]}
            key = (g, r)
            self._barriers.setdefault(key, set()).add(h)
            # completed earlier rounds can never be waited on again
            for k in [k for k in self._barriers
                      if k[0] == g and k[1] < r - 2]:
                del self._barriers[k]
            while True:
                if self._regen_wanted:
                    return {"ok": 0, "gen": self._gen}
                if self._spec is None or self._spec["gen"] != g:
                    return {"ok": 0, "gen": self._gen}
                if self._barriers.get(key, set()) >= members:
                    return {"ok": 1, "gen": g}
                left = deadline - time.monotonic()
                if left <= 0:
                    # a member never arrived: the generation is broken
                    missing = members - self._barriers.get(key, set())
                    self._break(f"barrier round {r} timed out waiting "
                                f"for {sorted(missing)}")
                    return {"ok": 0, "gen": self._gen}
                self._lock.wait(timeout=min(left, 0.25))

    # ------------------------------------------------------------------

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self.barrier_timeout + 30)
            req, _ = _recv_msg(conn)
            resp = self._handle(req)
            with self._lock:
                self._lock.notify_all()
            _send_msg(conn, resp)
        except (OSError, ConnectionError, json.JSONDecodeError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            self._lock.notify_all()    # release the cutter promptly
        try:
            self._srv.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# ElasticSupervisor — the per-host daemon (never runs JAX itself)
# ---------------------------------------------------------------------------

class ElasticSupervisor:
    """Owns one host's participation across generations: registers with
    the controller, prepares genesis/store from the generation's donor,
    spawns the worker process (and the unmodified app under the shim),
    serves its own dumps to other hosts, and reports failures."""

    def __init__(self, *, host_id: int, controller: str, workdir: str,
                 port: int = 0, app_port: int = 0, app_cmd: str = "",
                 round_iters: int = 25, cfg_json: str = "",
                 worker_env: Optional[dict] = None):
        # conn ids pack the host id into bits 24+ of an int32 log column;
        # enforce the bound HERE (where elastic host ids are chosen) so
        # an oversized id fails one supervisor at startup instead of
        # crashing every generation that includes it (the worker's
        # ProxyServer would raise the same bound mid-generation,
        # breaking the whole world in a regen loop)
        if not 0 <= host_id < 128:
            raise ValueError(
                f"host_id {host_id} out of range: conn-id origin field "
                "allows 0..127 — recycle retired host ids")
        self.host_id = host_id
        self.controller = controller
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.app_port = app_port
        self.app_cmd = app_cmd
        self.round_iters = round_iters
        self.cfg_json = cfg_json
        self.worker_env = worker_env or {}
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", port))
        self._srv.listen(16)
        self.addr = "127.0.0.1:%d" % self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._last_gen = 0
        self._child: Optional[subprocess.Popen] = None
        self._app: Optional[subprocess.Popen] = None
        # the recovery point offered for the NEXT generation, frozen at
        # registration time (no worker is running then, so the store
        # file is quiescent); donor fetches serve exactly this
        self._offered: Optional[Tuple[dict, bytes, dict]] = None
        threading.Thread(target=self._serve, daemon=True).start()

    # ---------------- dump serving (the donor side) ----------------

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(60)
            req, _ = _recv_msg(conn)
            if req.get("op") == "fetch":
                # serve the FROZEN offer captured at registration: the
                # live store may be getting replaced by our own _prepare
                # concurrently, and every member of the cut must see the
                # same donor state the controller elected on
                d = self._offered
                if d is None:
                    _send_msg(conn, {"ok": 0})
                else:
                    row, store, meta = d
                    _send_msg(conn, {"ok": 1, "meta": meta},
                              (_row_to_npz(row), store))
            else:
                _send_msg(conn, {"error": "unknown op"})
        except (OSError, ConnectionError, json.JSONDecodeError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # ---------------- generation lifecycle ----------------

    def _prepare(self, spec: dict) -> None:
        """Install the donor's state + store for the coming generation
        (uniformly for every member — see module docstring)."""
        from rdma_paxos_tpu.proxy.stablestore import StableStore
        donor = int(spec["donor"])
        if donor < 0:
            return
        if donor == self.host_id:
            d = self._offered
            assert d is not None, "donor lost its own recovery point"
            row_npz, store_blob, donor_meta = (_row_to_npz(d[0]), d[1],
                                               d[2])
        else:
            resp, blobs = call(spec["donor_addr"], {"op": "fetch"})
            if not resp.get("ok"):
                raise RuntimeError("donor has no dump to serve")
            row_npz, store_blob, donor_meta = (blobs[0], blobs[1],
                                               resp["meta"])
        base = os.path.join(self.workdir,
                            f"gen{spec['gen']}_donor")
        with open(f"{base}_row_h{self.host_id}.npz", "wb") as f:
            f.write(row_npz)
        with open(f"{base}_meta_h{self.host_id}.json", "w") as f:
            json.dump(donor_meta, f)
        # the old per-iteration rowdump pairs with the OLD store
        # contents: remove it BEFORE the store is replaced (a supervisor
        # killed in between then merely falls back to its consistent
        # barrier dump, instead of mis-pairing the old row with the new
        # store); our _offered copy keeps the old point safe in memory
        try:
            os.unlink(rowdump_path(self.workdir, self.host_id))
        except OSError:
            pass
        store = StableStore(os.path.join(self.workdir,
                                         f"host{self.host_id}.db"))
        try:
            store.reset()
            if store_blob:
                store.load(store_blob)
            store.sync()
        finally:
            store.close()

    def _spawn(self, spec: dict) -> None:
        members = [m["host"] for m in spec["members"]]
        slot = members.index(self.host_id)
        sock_path = os.path.join(self.workdir, f"proxy{slot}.sock")
        # a worker hard-killed mid-generation leaves its socket file
        # behind; matching it below would start the app against a dead
        # socket — the shim's connect fails and it silently serves
        # unreplicated. Remove it BEFORE the worker spawns (racing the
        # new worker's own bind would delete the live socket instead).
        try:
            os.unlink(sock_path)
        except OSError:
            pass
        spec_path = os.path.join(
            self.workdir, f"gen{spec['gen']}_spec_h{self.host_id}.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f)
        env = dict(os.environ)
        env.update(self.worker_env)
        env["PYTHONUNBUFFERED"] = "1"
        argv = [sys.executable, "-m",
                "rdma_paxos_tpu.runtime.elastic_worker",
                "--spec", spec_path, "--workdir", self.workdir,
                "--host-id", str(self.host_id),
                "--controller", self.controller,
                "--app-port", str(self.app_port),
                "--round-iters", str(self.round_iters)]
        if self.cfg_json:
            argv += ["--cfg-json", self.cfg_json]
        log = open(os.path.join(self.workdir,
                                f"worker_h{self.host_id}.log"), "ab")
        # keep a LOCAL handle: stop()/_reap() null self._child from
        # another thread, and dereferencing the attribute mid-wait was
        # a use-after-null crash (AttributeError spew on teardown)
        child = subprocess.Popen(argv, env=env, stdout=log,
                                 stderr=subprocess.STDOUT)
        self._child = child
        log.close()
        if self._stop.is_set():
            # stop() raced the Popen: its kill() saw _child as None, so
            # nothing would ever reap this worker — kill it here
            child.kill()
        if self.app_port:
            deadline = time.monotonic() + 120
            while (not os.path.exists(sock_path)
                   and child.poll() is None
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            if os.path.exists(sock_path):
                native = os.path.join(os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))),
                    "native")
                cmd = (self.app_cmd.split() if self.app_cmd
                       else [os.path.join(native, "toyserver"),
                             str(self.app_port)])
                aenv = dict(os.environ)
                aenv["LD_PRELOAD"] = os.path.join(native, "interpose.so")
                aenv["RP_PROXY_SOCK"] = sock_path
                self._app = subprocess.Popen(
                    cmd, env=aenv, stderr=subprocess.DEVNULL)
                print(f"supervisor h{self.host_id}: app started on "
                      f"port {self.app_port} (gen {spec['gen']}, pid "
                      f"{self._app.pid})", flush=True)
            else:
                print(f"supervisor h{self.host_id}: worker sock never "
                      f"appeared (gen {spec['gen']}) — app NOT started",
                      flush=True)

    def _reap(self) -> None:
        # swap-then-use: stop() and the run thread both reap; a local
        # handle makes the pair idempotent and race-free
        app, self._app = self._app, None
        if app is not None:
            app.kill()
            app.wait()
        self._child = None

    def run(self) -> None:
        """Supervisor main loop: register → wait for a generation that
        includes this host → prepare → run the worker → repeat."""
        while not self._stop.is_set():
            # freeze the recovery point we offer this cycle (no worker
            # is running, so the store file is quiescent right now)
            self._offered = best_recovery(self.workdir, self.host_id)
            try:
                call(self.controller,
                     {"op": "register", "host": self.host_id,
                      "addr": self.addr,
                      "meta": (self._offered[2]
                               if self._offered else None)})
            except (OSError, ConnectionError):
                time.sleep(0.5)
                continue
            spec = None
            while not self._stop.is_set():
                try:
                    resp, _ = call(self.controller,
                                   {"op": "poll",
                                    "host": self.host_id})
                except (OSError, ConnectionError):
                    time.sleep(0.5)
                    continue
                if resp.get("ok") and resp["gen"] > self._last_gen:
                    spec = resp
                    break
                time.sleep(0.15)
            if spec is None:
                break
            self._last_gen = spec["gen"]
            try:
                self._prepare(spec)
                self._spawn(spec)
                child = self._child
                rc = child.wait() if child is not None else -1
            except Exception:
                rc = -1
                if not self._stop.is_set():
                    # a stop() racing the spawn is an expected shutdown
                    # path, not a fault — only real failures may print
                    import traceback
                    traceback.print_exc()
            finally:
                self._reap()
            if rc != 0 and not self._stop.is_set():
                try:
                    call(self.controller, {"op": "fail",
                                           "host": self.host_id,
                                           "gen": spec["gen"]})
                except (OSError, ConnectionError):
                    pass

    def stop(self) -> None:
        self._stop.set()
        # local handle: the run thread's _reap() may null the attribute
        # between a check and the kill (the same use-after-null class
        # fixed in _spawn) — read once, then act on the copy
        child = self._child
        if child is not None:
            child.kill()
        self._reap()
        try:
            self._srv.close()
        except OSError:
            pass


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host-id", type=int, required=True)
    ap.add_argument("--controller", required=True)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--app-port", type=int, default=0)
    ap.add_argument("--app-cmd", default="")
    ap.add_argument("--round-iters", type=int, default=25)
    ap.add_argument("--cfg-json", default="")
    ap.add_argument("--worker-cpu", action="store_true",
                    help="run worker consensus cores on the CPU backend "
                         "(sets RP_BENCH_CPU=1 for workers; without this "
                         "workers inherit the environment's backend — on "
                         "a TPU host that means the TPU)")
    args = ap.parse_args()
    sup = ElasticSupervisor(
        host_id=args.host_id, controller=args.controller,
        workdir=args.workdir, port=args.port, app_port=args.app_port,
        app_cmd=args.app_cmd, round_iters=args.round_iters,
        cfg_json=args.cfg_json,
        worker_env={"RP_BENCH_CPU": "1"} if args.worker_cpu else None)
    print(f"supervisor h{args.host_id} serving on {sup.addr}",
          flush=True)
    try:
        sup.run()
    finally:
        sup.stop()


if __name__ == "__main__":
    main()
