"""Elastic generation worker — one per host per generation.

Runs the ordinary :class:`~rdma_paxos_tpu.runtime.node.NodeDaemon`
lock-step loop inside the generation's own ``jax.distributed`` world,
bracketed by the elastic machinery of :mod:`.elastic`:

* boots from the generation's GENESIS row (donor state sanitized by
  :func:`~rdma_paxos_tpu.consensus.snapshot.genesis_row`) when the spec
  names a donor, else fresh;
* rebuilds the local app by replaying the (donor-derived) stable store;
* between rounds of ``--round-iters`` iterations, dumps a consistent
  (state row, store blob, meta) recovery triple and posts the
  controller's round barrier — ``ok=0`` means the world is being rebuilt
  and this worker exits cleanly;
* on ANY collective error (a peer died mid-round) the last barrier dump
  on disk is the recovery point; the worker exits nonzero and the
  supervisor reports the failure.

Exit codes: 0 = clean generation end; nonzero = collective/peer failure.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--spec", required=True)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--host-id", type=int, required=True)
    ap.add_argument("--controller", required=True)
    ap.add_argument("--app-port", type=int, default=0)
    ap.add_argument("--round-iters", type=int, default=25)
    ap.add_argument("--cfg-json", default="")
    args = ap.parse_args()

    with open(args.spec) as f:
        spec = json.load(f)
    members = [m["host"] for m in spec["members"]]
    slot = members.index(args.host_id)
    M = len(members)

    # Backend selection: force CPU only when EXPLICITLY requested
    # (RP_BENCH_CPU=1); otherwise the worker inherits the environment's
    # backend, so a TPU deployment runs the consensus core on the TPU
    # rather than silently falling to CPU (advisor finding r3). The
    # override must go through jax.config — a sitecustomize may have
    # force-set jax_platforms at interpreter start, which an env var
    # cannot undo. The choice is logged so a misconfig is visible.
    force_cpu = os.environ.get("RP_BENCH_CPU") == "1"
    if force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)     # one device per process
    import jax
    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    print(f"worker h{args.host_id}: backend="
          f"{'cpu (forced, RP_BENCH_CPU=1)' if force_cpu else 'inherited'}"
          f" JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '<default>')}",
          flush=True)

    from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
    from rdma_paxos_tpu.consensus.snapshot import genesis_row
    from rdma_paxos_tpu.runtime.elastic import (call, write_dump,
                                                write_rowdump)
    from rdma_paxos_tpu.runtime.node import NodeDaemon

    if args.cfg_json:
        raw = json.loads(args.cfg_json)
        cfg = LogConfig(**raw.get("log", {}))
        timing = TimeoutConfig(**raw.get("timing", {}))
    else:
        cfg = LogConfig(n_slots=1024, slot_bytes=256, window_slots=64,
                        batch_slots=64)
        timing = TimeoutConfig(elec_timeout_low=0.5, elec_timeout_high=1.0)

    genesis = None
    if int(spec["donor"]) >= 0:
        import numpy as np
        base = os.path.join(args.workdir, f"gen{spec['gen']}_donor")
        with np.load(f"{base}_row_h{args.host_id}.npz") as z:
            donor_row = {k: z[k] for k in z.files}
        with open(f"{base}_meta_h{args.host_id}.json") as f:
            donor_meta = json.load(f)
        genesis = genesis_row(
            donor_row, group_mask=(1 << M) - 1, epoch=int(spec["epoch"]),
            n_replicas=M, term=int(spec["term_base"]))
        # the store blob matches the donor's HOST applied counter (the
        # device-row apply can lag it by the final iteration's window);
        # raise apply to the store's high-water mark so no member
        # re-applies — and so re-appends — records already in the store
        genesis["apply"] = np.int32(int(donor_meta["applied"]))

    node = NodeDaemon(
        cfg, process_id=slot, num_processes=M,
        coordinator=spec["coordinator"], workdir=args.workdir,
        app_port=args.app_port or None, timeout_cfg=timing,
        host_id=args.host_id, genesis=genesis,
        seed=spec["gen"] * 1000, gen=int(spec["gen"]))
    # COLLECTIVE: compile the burst program before serving (no-op when
    # bursts are disabled for this backend) — the multi-process compile
    # must never land mid-drain (the persistent cache does not serve
    # these programs)
    node.prewarm_burst()

    if args.app_port:
        # the supervisor starts the app once our proxy socket exists;
        # wait until it accepts before replaying history into it. A
        # missing app is FATAL, not skippable: booting consensus with an
        # app that missed its history bootstrap serves wrong data.
        deadline = time.monotonic() + 120
        while True:
            try:
                socket.create_connection(("127.0.0.1", args.app_port),
                                         timeout=2).close()
                break
            except OSError:
                if time.monotonic() >= deadline:
                    print(f"FATAL: app on port {args.app_port} never "
                          "came up; aborting generation", flush=True)
                    os._exit(1)
                time.sleep(0.1)
    node.bootstrap_from_store()
    print(f"gen {spec['gen']}: bootstrapped app from "
          f"{len(node.store)} store records (applied={node.applied})",
          flush=True)

    gen, rnd = int(spec["gen"]), 0
    # Per-iteration RECOVERY POINT on disk: a worker can be killed
    # instantly and un-catchably — the JAX coordination-service client
    # LOG(FATAL)s the process the moment it learns a peer died, often
    # beating the catchable collective error — so no crash handler can
    # be relied on. After every completed iteration the (row, meta +
    # live-store length) pair is renamed into place (atomic vs process
    # death); recovery pairs it with the live store trimmed to that
    # length (elastic.best_recovery), so the freshest recovery point —
    # containing every write acked so far — is never more than one
    # iteration old, however the process dies.
    last_progress = None
    try:
        while True:
            row = meta = None
            for _ in range(args.round_iters):
                res = node.iterate()
                # recovery points only need refreshing when the state
                # advanced — an ack implies progress in that iteration,
                # so acked writes are always covered; idle iterations
                # skip the row serialization + write entirely
                progress = (node.applied, int(res["term"]),
                            int(res["end"]), int(res["commit"]))
                if row is None or progress != last_progress:
                    last_progress = progress
                    row = node.dump_row()
                    meta = node.meta(row)
                    meta.update(gen=gen, round=rnd, host=args.host_id,
                                store_len=len(node.store))
                    write_rowdump(args.workdir, args.host_id, row, meta)
                if node.needs_recovery:
                    # force-pruned past our apply cursor: this world
                    # can no longer serve through us — trigger a
                    # rebuild in which the donor's store restores our
                    # app (our meta carries usable=0: never the donor)
                    raise RuntimeError(
                        "force-pruned past apply cursor; requesting "
                        "world rebuild for snapshot recovery")
                if node.app_dirty:
                    # mis-speculation quarantine: the app executed
                    # input that can no longer commit (deposed mid
                    # flight) and must not serve again. Within a
                    # generation nothing restarts the app process, so
                    # convert the quarantine into a world rebuild —
                    # the supervisor spawns a FRESH app and the next
                    # generation bootstraps it from the committed
                    # store. The store itself is clean (it only ever
                    # holds committed entries), so our dump remains a
                    # usable recovery point.
                    raise RuntimeError(
                        "speculative app diverged (app_dirty); "
                        "requesting world rebuild for an app restart")
            # round barrier + a DURABLE full dump (fsynced triple —
            # the power-loss-safe recovery tier); a fully idle round
            # leaves the previous dump standing
            if row is not None:
                write_dump(args.workdir, args.host_id, row,
                           node.store.dump(), meta)
            try:
                resp, _ = call(
                    args.controller,
                    {"op": "round", "host": args.host_id,
                     "gen": gen, "round": rnd},
                    # must outlive the controller's barrier budget
                    timeout=float(spec.get("barrier_timeout", 120)) + 60)
            except (OSError, ConnectionError):
                resp = {"ok": 0}
            if not resp.get("ok"):
                break
            rnd += 1
    except Exception:
        import traceback
        traceback.print_exc()
        # the per-iteration rowdump on disk is the recovery point; exit
        # hard so the wedged distributed runtime cannot block us (its
        # shutdown barrier would abort anyway once a peer is gone)
        sys.stdout.flush()
        os._exit(1)
    node.close()
    # skip jax.distributed shutdown: peers may already be gone and the
    # coordination-service shutdown barrier would turn a clean exit into
    # an abort; the dump is already on disk
    sys.stdout.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
