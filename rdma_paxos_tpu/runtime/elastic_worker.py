"""Elastic generation worker — one per host per generation.

Runs the ordinary :class:`~rdma_paxos_tpu.runtime.node.NodeDaemon`
lock-step loop inside the generation's own ``jax.distributed`` world,
bracketed by the elastic machinery of :mod:`.elastic`:

* boots from the generation's GENESIS row (donor state sanitized by
  :func:`~rdma_paxos_tpu.consensus.snapshot.genesis_row`) when the spec
  names a donor, else fresh;
* rebuilds the local app by replaying the (donor-derived) stable store;
* between rounds of ``--round-iters`` iterations, dumps a consistent
  (state row, store blob, meta) recovery triple and posts the
  controller's round barrier — ``ok=0`` means the world is being rebuilt
  and this worker exits cleanly;
* on ANY collective error (a peer died mid-round) the last barrier dump
  on disk is the recovery point; the worker exits nonzero and the
  supervisor reports the failure.

Exit codes: 0 = clean generation end; nonzero = collective/peer failure.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--spec", required=True)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--host-id", type=int, required=True)
    ap.add_argument("--controller", required=True)
    ap.add_argument("--app-port", type=int, default=0)
    ap.add_argument("--round-iters", type=int, default=25)
    ap.add_argument("--cfg-json", default="")
    args = ap.parse_args()

    with open(args.spec) as f:
        spec = json.load(f)
    members = [m["host"] for m in spec["members"]]
    slot = members.index(args.host_id)
    M = len(members)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("XLA_FLAGS", None)     # one device per process
    import jax
    if os.environ.get("RP_BENCH_CPU", "1") == "1":
        jax.config.update("jax_platforms", "cpu")

    from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
    from rdma_paxos_tpu.consensus.snapshot import genesis_row
    from rdma_paxos_tpu.runtime.elastic import call, write_dump
    from rdma_paxos_tpu.runtime.node import NodeDaemon

    if args.cfg_json:
        raw = json.loads(args.cfg_json)
        cfg = LogConfig(**raw.get("log", {}))
        timing = TimeoutConfig(**raw.get("timing", {}))
    else:
        cfg = LogConfig(n_slots=1024, slot_bytes=256, window_slots=64,
                        batch_slots=64)
        timing = TimeoutConfig(elec_timeout_low=0.5, elec_timeout_high=1.0)

    genesis = None
    if int(spec["donor"]) >= 0:
        import numpy as np
        base = os.path.join(args.workdir, f"gen{spec['gen']}_donor")
        with np.load(f"{base}_row_h{args.host_id}.npz") as z:
            donor_row = {k: z[k] for k in z.files}
        with open(f"{base}_meta_h{args.host_id}.json") as f:
            donor_meta = json.load(f)
        genesis = genesis_row(
            donor_row, group_mask=(1 << M) - 1, epoch=int(spec["epoch"]),
            n_replicas=M, term=int(spec["term_base"]))
        # the store blob matches the donor's HOST applied counter (the
        # device-row apply can lag it by the final iteration's window);
        # raise apply to the store's high-water mark so no member
        # re-applies — and so re-appends — records already in the store
        genesis["apply"] = np.int32(int(donor_meta["applied"]))

    node = NodeDaemon(
        cfg, process_id=slot, num_processes=M,
        coordinator=spec["coordinator"], workdir=args.workdir,
        app_port=args.app_port or None, timeout_cfg=timing,
        host_id=args.host_id, genesis=genesis,
        seed=spec["gen"] * 1000, gen=int(spec["gen"]))

    if args.app_port:
        # the supervisor starts the app once our proxy socket exists;
        # wait until it accepts before replaying history into it
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                socket.create_connection(("127.0.0.1", args.app_port),
                                         timeout=2).close()
                break
            except OSError:
                time.sleep(0.1)
    node.bootstrap_from_store()

    gen, rnd = int(spec["gen"]), 0
    # Per-iteration stash: after every COMPLETED iteration, keep the
    # state row + meta in memory. On a mid-round collective failure (a
    # peer died), the live store sits exactly at the stashed iteration
    # (the failing step never reached its apply phase), so the pair is a
    # CONSISTENT recovery point that includes every write acked so far —
    # this is what makes "acked writes survive any tolerated failure"
    # true even for failures between round barriers.
    stash_row = stash_meta = None
    try:
        while True:
            for _ in range(args.round_iters):
                node.iterate()
                stash_row = node.dump_row()
                stash_meta = node.meta(stash_row)
                stash_meta.update(gen=gen, round=rnd,
                                  host=args.host_id)
                if node.needs_recovery:
                    # force-pruned past our apply cursor: this world can
                    # no longer serve through us — trigger a rebuild in
                    # which the donor's store restores our app. The
                    # detecting iteration touched neither store nor app,
                    # so the stash pair is consistent; dump it now
                    # (meta carries usable=0, so we cannot be donor).
                    write_dump(args.workdir, args.host_id, stash_row,
                               node.store.dump(), stash_meta)
                    raise RuntimeError(
                        "force-pruned past apply cursor; requesting "
                        "world rebuild for snapshot recovery")
            write_dump(args.workdir, args.host_id, stash_row,
                       node.store.dump(), stash_meta)
            try:
                resp, _ = call(
                    args.controller,
                    {"op": "round", "host": args.host_id,
                     "gen": gen, "round": rnd},
                    # must outlive the controller's barrier budget
                    timeout=float(spec.get("barrier_timeout", 120)) + 60)
            except (OSError, ConnectionError):
                resp = {"ok": 0}
            if not resp.get("ok"):
                break
            rnd += 1
    except Exception:
        import traceback
        traceback.print_exc()
        # dump the stash UNLESS the failure hit the apply phase (then
        # the live store may be mid-iteration, ahead of the stashed row
        # — fall back to the last barrier dump already on disk)
        if stash_row is not None and node.phase == "step":
            try:
                write_dump(args.workdir, args.host_id, stash_row,
                           node.store.dump(), stash_meta)
            except Exception:
                traceback.print_exc()
        # exit hard so the wedged distributed runtime cannot block us
        # (its shutdown barrier would abort anyway once a peer is gone)
        sys.stdout.flush()
        os._exit(1)
    node.close()
    # skip jax.distributed shutdown: peers may already be gone and the
    # coordination-service shutdown barrier would turn a clean exit into
    # an abort; the dump is already on disk
    sys.stdout.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
