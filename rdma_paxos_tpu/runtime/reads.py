"""Read scaling — leader leases and read-index follower reads.

Every linearizable GET used to ride the replicated log (appended,
quorum-acked, committed, applied like a write), so read throughput was
capped at the committed-ops/s ceiling and every read burned ring
headroom. This module builds the read path as a first-class HOST-SIDE
subsystem — zero device changes: STEP_CACHE keys and compiled programs
are bit-identical with it attached (``tests/test_reads.py`` pins it).

**Leader leases** (:class:`LeaseManager`) — step-domain leases
piggybacked on the quorum machinery the protocol already runs: every
finished step whose outputs show a leader with
``leadership_verified`` (a majority acked its window — the heartbeat
round) RENEWS that leader's lease for its group. A leaseholder serves
linearizable reads from its local applied state with zero log
traffic. Safety is conservative under the timeout skew the chaos
nemesis injects:

* validity is ``now - last_verified < lease_steps`` in FINISHED-step
  time, with ``now`` taken as ``max(step_index, dispatch_clock)`` so
  in-flight pipelined dispatches age the lease, never extend it;
* ``lease_steps`` defaults to 2: even a maximally skew-accelerated
  rival (an election timer firing ONE step after the holder's last
  verified quorum) needs one step to win votes and one more to commit
  — so the usurper's first committed write always lands STRICTLY
  after the deposed holder's last possible lease serve;
* a new leader must wait out the old lease before its own activates
  (``barrier`` = old ``last_verified + lease_steps + guard_steps``);
  until then it serves reads only through the read-index path;
* deposition, ``need_recovery``, repair quarantine, and step-down all
  revoke immediately (the step-count expiry is the load-bearing
  guard; revocation is hygiene that also feeds the trace timeline).

**Read-index follower reads** (:class:`ReadHub`) — a queued read at
replica ``f`` confirms the leader's commit index ONCE (from a
finished step where the leader verified leadership), waits for ``f``'s
local apply frontier to reach it, then serves from ``f``'s state —
fanning read load across all R replicas. The hub's queue is drained
at the tail of the engines' ``finish()``, which under the pipelined
drivers runs on the READBACK thread — reads interleave between
pipelined tickets and never enter ``begin_*``, never consume ring
slots, never perturb the compiled step.

Served reads export ``reads_served_total{path=lease|read_index|log,
replica=,group=}`` counters, a ``read_latency_us`` histogram, and a
cheap read-span variant on the span recorder; lease transitions
(grant / renew / expire / revoke) ride the protocol trace ring.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.obs import trace as obs_trace
from rdma_paxos_tpu.obs.metrics import LATENCY_BUCKETS_US

_LEADER = int(Role.LEADER)


def leader_claim(role_row, term_row, n: int) -> Tuple[int, int]:
    """Highest-term self-claimed leader of one group's result rows —
    the drivers' failover view rule (terms are unique per leader by
    quorum election, so max-term picks the real one). Returns
    ``(leader, term)``, ``(-1, 0)`` when nobody claims. The ONE copy
    this module uses for both lease observation and hub confirmation."""
    best_r, best_t = -1, 0
    for r in range(n):
        if int(role_row[r]) == _LEADER:
            t = int(term_row[r])
            if best_r < 0 or t > best_t:
                best_r, best_t = r, t
    return best_r, best_t

# served-path labels (the reads_served_total{path=} vocabulary)
PATH_LEASE = "lease"
PATH_READ_INDEX = "read_index"
PATH_LOG = "log"


def count_read(obs, path: str, replica: int, *,
               group: Optional[int] = None, t0: Optional[float] = None,
               n: int = 1) -> None:
    """Export one (or ``n``) served reads: the per-path counter, the
    latency histogram (when the caller timed it from ``t0``,
    ``time.monotonic``), and the cheap read-span variant. The ONE
    accounting rule every serving site (KVS sync reads, the hub, the
    bench's log-path baseline) shares, so the ``path=`` series always
    add up to the reads actually served."""
    if obs is None:
        return
    labels = dict(path=path, replica=replica)
    if group is not None:
        labels["group"] = group
    obs.metrics.inc("reads_served_total", n, **labels)
    if t0 is not None:
        now = time.monotonic()
        from rdma_paxos_tpu.obs.spans import active_recorder
        rec = active_recorder(obs)
        rid = None
        if rec is not None:
            # span first: a sampled read's id becomes the latency
            # histogram's exemplar, so a read-SLO page resolves to a
            # concrete read span on the merged timeline
            rid = rec.read_span(replica, path, t0,
                                group=(-1 if group is None else group))
        obs.metrics.observe("read_latency_us", (now - t0) * 1e6,
                            buckets=LATENCY_BUCKETS_US, exemplar=rid,
                            path=path)


def read_counts(obs) -> Dict[str, int]:
    """Per-path totals summed over replicas/groups from the registry —
    the deterministic accounting chaos verdicts and bench proofs
    embed."""
    out = {PATH_LEASE: 0, PATH_READ_INDEX: 0, PATH_LOG: 0}
    if obs is None:
        return out
    for key, v in obs.metrics.snapshot()["counters"].items():
        if not key.startswith("reads_served_total"):
            continue
        for path in out:
            if f"path={path}" in key:
                out[path] += int(v)
    return out


class _LeaseState:
    """Per-group lease bookkeeping (host dict ops only)."""

    __slots__ = ("holder", "active_from", "last_verified", "barrier",
                 "term", "expired_marked")

    def __init__(self):
        self.holder = -1          # current leader view (may be inactive)
        self.active_from = -1     # step the lease activated; -1 = none
        self.last_verified = -1   # newest verified-quorum step observed
        self.barrier = 0          # no lease may activate before this step
        self.term = 0
        self.expired_marked = False   # expire event emitted once per lapse

    def as_dict(self) -> dict:
        return dict(holder=self.holder, active_from=self.active_from,
                    last_verified=self.last_verified,
                    barrier=self.barrier, term=self.term)


class LeaseManager:
    """Step-domain per-group leader leases, renewed by the finished
    steps' verified-quorum outputs (see the module docstring for the
    safety argument). Engine-agnostic: :meth:`observe` handles both
    the ``[R]`` (SimCluster) and ``[G, R]`` (ShardedCluster — vmap or
    mesh) result shapes."""

    def __init__(self, n_groups: int = 1, *, lease_steps: int = 2,
                 guard_steps: int = 2, renew_trace_every: int = 16):
        if lease_steps < 1:
            raise ValueError("lease_steps must be >= 1")
        self.G = int(n_groups)
        self.lease_steps = int(lease_steps)
        self.guard_steps = int(guard_steps)
        self.renew_trace_every = max(1, int(renew_trace_every))
        # guarded-by: _lock
        self._st: List[_LeaseState] = [_LeaseState()
                                       for _ in range(self.G)]
        self._lock = threading.Lock()
        self._now = 0        # guarded-by: _lock [writes]
        self._now_max = 0    # guarded-by: _lock [writes]
        self._obs = None         # refreshed from the engine each observe
        self.grants = 0
        self.renewals = 0
        self.revocations = 0

    # ------------------------------------------------------------------
    # observation (engines' finish() tail — readback-thread safe)
    # ------------------------------------------------------------------

    def observe(self, engine, res) -> None:
        """Fold one finished step's outputs into the lease state:
        renew the verified leader's lease per group, revoke on
        deposition / leaderlessness / repair holds, and advance the
        conservative clocks. The leader-claim extraction is ONE
        vectorized numpy pass — this runs on the readback hot path
        every finished step, so a G×R Python scan would tax exactly
        the thread PR 6 moved work off of."""
        self._obs = getattr(engine, "obs", None)
        step = int(engine.step_index)
        disp = int(getattr(engine, "_dispatch_clock", step))
        role = np.asarray(res["role"])
        sharded = role.ndim == 2
        term = np.asarray(res["term"])
        ver = np.asarray(res["leadership_verified"])
        if not sharded:
            role, term, ver = role[None], term[None], ver[None]
        # per-group highest-term claimant (the leader_claim rule,
        # vectorized): mask non-claimants to -1, argmax the terms
        masked = np.where(role == _LEADER, term, -1)        # [G, R]
        leaders = masked.argmax(axis=1)
        has = masked[np.arange(masked.shape[0]), leaders] >= 0
        nr = engine.need_recovery
        rb = getattr(engine, "read_blocked", ())
        with self._lock:
            self._now = step
            # dispatch-ahead aging, CLAMPED: in-flight dispatches age
            # a lease (extra conservatism on top of the finished-step
            # safety argument) but may never fully cover the window —
            # unclamped, a pipeline depth >= lease_steps would expire
            # every lease the same observe that granted it, silently
            # disabling the lease path and churning grant/expire
            # events every verified step
            self._now_max = max(step, min(disp,
                                          step + self.lease_steps - 1))
            for g in range(self.G):
                leader = int(leaders[g]) if has[g] else -1
                key = (g, leader) if sharded else leader
                blocked = leader >= 0 and (key in nr or key in rb)
                verified = leader >= 0 and bool(ver[g, leader])
                lterm = int(masked[g, leader]) if leader >= 0 else 0
                self._observe_group(g, step, leader, lterm, verified,
                                    blocked)

    # holds-lock: _lock
    def _observe_group(self, g: int, step: int, leader: int,
                       term: int, verified: bool,
                       blocked: bool) -> None:
        st = self._st[g]
        if leader != st.holder:
            if st.holder >= 0:
                self._revoke_locked(
                    g, "deposed" if leader >= 0 else "leaderless")
            st.holder = leader
            st.term = term
            st.last_verified = -1
        if leader < 0:
            return
        st.term = term
        if blocked:
            if st.active_from >= 0:
                self._revoke_locked(g, "need_recovery")
            return
        if verified:
            active = (st.active_from >= 0 and not st.expired_marked
                      and step - st.last_verified <= self.lease_steps)
            if active:
                st.last_verified = step
                self.renewals += 1
                if self._obs is not None \
                        and (self.renewals - 1) \
                        % self.renew_trace_every == 0:
                    self._obs.trace.record(
                        obs_trace.LEASE_RENEWED, replica=leader,
                        group=g, step=step, term=term)
            elif step >= st.barrier:
                # grant (or re-grant after a lapse) — a NEW lease may
                # only activate once the previous holder's lease has
                # been waited out (the barrier); a lapsed lease of the
                # SAME still-unique leader re-activates immediately
                # (validity derives purely from verified-quorum
                # recency, and no rival can have been elected without
                # deposing it — which resets the holder above)
                st.active_from = step
                st.last_verified = step
                st.expired_marked = False
                self.grants += 1
                if self._obs is not None:
                    self._obs.metrics.inc("lease_grants_total",
                                          replica=leader, group=g)
                    self._obs.metrics.set("lease_holder", leader,
                                          group=g)
                    self._obs.trace.record(
                        obs_trace.LEASE_GRANTED, replica=leader,
                        group=g, step=step, term=term,
                        barrier=st.barrier)
        # natural expiry: emit the timeline event once per lapse
        if (st.active_from >= 0 and not st.expired_marked
                and self._now_max - st.last_verified
                >= self.lease_steps):
            st.expired_marked = True
            if self._obs is not None:
                self._obs.metrics.inc("lease_expired_total",
                                      replica=st.holder, group=g)
                self._obs.trace.record(
                    obs_trace.LEASE_EXPIRED, replica=st.holder,
                    group=g, step=step, last_verified=st.last_verified)

    # ------------------------------------------------------------------
    # queries / control
    # ------------------------------------------------------------------

    def valid(self, group: int, replica: int,
              now: Optional[int] = None) -> bool:
        """True iff ``replica`` holds an ACTIVE, unexpired lease for
        ``group`` at ``now`` (default: the conservative
        ``max(step_index, dispatch_clock)`` of the last observe)."""
        with self._lock:
            st = self._st[group]
            if st.holder != replica or st.active_from < 0 \
                    or st.last_verified < 0:
                return False
            n = self._now_max if now is None else int(now)
            return n - st.last_verified < self.lease_steps

    def serving_holder(self, group: int) -> int:
        """The replica currently able to serve lease reads for
        ``group`` (-1 when none)."""
        with self._lock:
            st = self._st[group]
            holder = st.holder
        if holder >= 0 and self.valid(group, holder):
            return holder
        return -1

    def holders(self) -> List[int]:
        return [self.serving_holder(g) for g in range(self.G)]

    def revoke(self, group: int, replica: int, reason: str) -> bool:
        """External revocation (repair quarantine, driver step-down):
        immediately invalidates ``replica``'s lease for ``group`` and
        arms the wait-out barrier. No-op when it holds no lease."""
        with self._lock:
            st = self._st[group]
            if st.holder != replica:
                return False
            return self._revoke_locked(group, reason)

    def revoke_any(self, group: int, reason: str) -> bool:
        """Revoke whatever lease ``group`` currently has, holder
        unknown to the caller — the topology cutover's fence (serving
        through a lease granted under the OLD routing must provably
        stop before the router swaps). No-op when nothing is held."""
        with self._lock:
            return self._revoke_locked(group, reason)

    def revoke_all(self, replica: int, reason: str) -> int:
        """Revoke every group lease ``replica`` holds (driver
        step-down / crash paths)."""
        n = 0
        for g in range(self.G):
            if self.revoke(g, replica, reason):
                n += 1
        return n

    def _revoke_locked(self, g: int, reason: str) -> bool:
        st = self._st[g]
        had = st.active_from >= 0
        if st.last_verified >= 0:
            # waiter-side conservative expiry of the old lease: no new
            # lease may activate before it has provably lapsed even
            # under the in-flight/pipelined clock uncertainty
            st.barrier = max(st.barrier, st.last_verified
                             + self.lease_steps + self.guard_steps)
        holder = st.holder
        st.active_from = -1
        st.expired_marked = False
        if had:
            self.revocations += 1
            if self._obs is not None:
                self._obs.metrics.inc("lease_revoked_total",
                                      replica=holder, group=g,
                                      reason=reason)
                self._obs.metrics.set("lease_holder", -1, group=g)
                self._obs.trace.record(
                    obs_trace.LEASE_REVOKED, replica=holder, group=g,
                    step=self._now, reason=reason)
        return had

    def status(self) -> dict:
        """Deterministic (step-domain) export for health snapshots and
        chaos verdicts."""
        with self._lock:
            return dict(
                lease_steps=self.lease_steps,
                guard_steps=self.guard_steps,
                now=self._now, now_max=self._now_max,
                grants=self.grants, renewals=self.renewals,
                revocations=self.revocations,
                groups=[st.as_dict() for st in self._st],
                holders=[(st.holder
                          if st.active_from >= 0 and st.last_verified >= 0
                          and self._now_max - st.last_verified
                          < self.lease_steps else -1)
                         for st in self._st],
            )


class ReadTicket:
    """One queued linearizable read: submitted from any thread, served
    (or failed) by the hub drain on the finishing thread."""

    __slots__ = ("group", "replica", "serve_fn", "on_done", "patience",
                 "step0", "t0", "read_index", "path", "value", "status",
                 "pass_ticket", "_ev")

    def __init__(self, serve_fn, replica: int, group: int,
                 patience: int, step0: int, on_done,
                 pass_ticket: bool = False):
        self.serve_fn = serve_fn
        self.pass_ticket = bool(pass_ticket)
        self.replica = int(replica)
        self.group = int(group)
        self.patience = int(patience)
        self.step0 = int(step0)
        self.t0 = time.monotonic()
        self.read_index: Optional[int] = None   # absolute, once confirmed
        self.path: Optional[str] = None
        self.value = None
        self.status: Optional[str] = None       # None | "ok" | "failed"
        self.on_done = on_done
        self._ev = threading.Event()

    @property
    def done(self) -> bool:
        return self.status is not None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._ev.wait(timeout)


class ReadHub:
    """The drivers' read queue: linearizable reads queued from client
    threads, drained at the tail of every finished step (the readback
    thread under pipelining) — between pipelined tickets, never
    inside one. Path selection per read, in order: the replica's
    valid LEASE (serve from local applied state, zero log traffic),
    else READ-INDEX (confirm the verified leader's commit index once,
    wait for the local apply frontier to reach it), else keep queued
    until the step-domain patience lapses (fail — the read
    definitively did not happen, so it constrains nothing)."""

    def __init__(self, leases: Optional[LeaseManager] = None, *,
                 patience_steps: int = 64):
        self.leases = leases
        self.patience_steps = int(patience_steps)
        self._lock = threading.Lock()
        # guarded-by: _lock [strict]
        self._q: collections.deque = collections.deque()
        # guarded-by: _lock
        self.served: Dict[str, int] = {PATH_LEASE: 0,
                                       PATH_READ_INDEX: 0}
        self.failed = 0    # guarded-by: _lock
        # runtime lock sanitizer: _q is [strict] — under RP_SANITIZE=1
        # even READS assert the hub lock (no lock-free read exists)
        from rdma_paxos_tpu.analysis import runtime_guard
        runtime_guard.maybe_guard(self, "_lock", __file__)

    def submit(self, serve_fn: Optional[Callable] = None, *,
               replica: int, group: int = 0,
               patience: Optional[int] = None,
               step0: Optional[int] = None, on_done=None,
               pass_ticket: bool = False) -> ReadTicket:
        """Queue a read at ``replica`` (thread-safe). ``step0`` anchors
        the step-domain patience; without it the first drain stamps
        the current finished step (a client thread rarely knows the
        engine clock). ``pass_ticket=True`` calls ``serve_fn(ticket)``
        instead of ``serve_fn()`` — the serve callback runs AT the
        linearization point, and a range scan needs the confirmed
        ``read_index`` there to pin its consistent cut."""
        t = ReadTicket(serve_fn, replica, group,
                       self.patience_steps if patience is None
                       else patience,
                       -1 if step0 is None else step0, on_done,
                       pass_ticket)
        with self._lock:
            self._q.append(t)
        return t

    def pending_count(self) -> int:
        with self._lock:
            return len(self._q)

    # ------------------------------------------------------------------

    def _commit(self, t: ReadTicket, status: str, path: Optional[str],
                value) -> bool:
        """Atomically move a ticket to its terminal state; False when
        another completer already won. FIRST COMPLETION WINS: the
        stop-path ``fail_all`` can race the readback thread's drain
        over the same ticket, and a double completion would flip a
        client-visible status and fire ``on_done`` twice."""
        with self._lock:
            if t.status is not None:
                return False
            t.status = status
            t.path = path
            t.value = value
            if status == "ok":
                self.served[path] = self.served.get(path, 0) + 1
            else:
                self.failed += 1
        if t.on_done is not None:
            try:
                t.on_done(t.status, t.value)
            except Exception:  # noqa: BLE001 — callbacks never kill
                pass           # the finishing thread
        t._ev.set()
        return True

    def _finish(self, obs, t: ReadTicket, path: Optional[str],
                ok: bool) -> None:
        if not ok:
            self._commit(t, "failed", None, None)
            return
        try:
            if t.serve_fn is None:
                value = None
            elif t.pass_ticket:
                value = t.serve_fn(t)
            else:
                value = t.serve_fn()
        except Exception:  # noqa: BLE001 — a failing serve callback
            # must fail THE READ, never the finishing (readback)
            # thread the whole data path runs on
            self._commit(t, "failed", path, None)
            return
        if self._commit(t, "ok", path, value):
            count_read(obs, path, t.replica, group=t.group, t0=t.t0)

    def drain(self, engine) -> int:
        """Serve every due queued read against ``engine``'s last
        FINISHED step (called from the engines' ``finish()`` tail).
        Returns the number of reads resolved this pass."""
        res = engine.last
        if res is None:
            return 0
        with self._lock:
            if not self._q:
                return 0
            pending = list(self._q)
        obs = getattr(engine, "obs", None)
        sharded = res["role"].ndim == 2
        now = int(engine.step_index)
        nr = engine.need_recovery
        rb = getattr(engine, "read_blocked", ())
        views: Dict[int, tuple] = {}

        def view(g: int):
            v = views.get(g)
            if v is None:
                role = res["role"][g] if sharded else res["role"]
                term = res["term"][g] if sharded else res["term"]
                ver = (res["leadership_verified"][g] if sharded
                       else res["leadership_verified"])
                commit = res["commit"][g] if sharded else res["commit"]
                applied = (engine.applied[g] if sharded
                           else engine.applied)
                reb = (int(engine.rebased_total[g]) if sharded
                       else int(engine.rebased_total))
                leader, _t = leader_claim(role, term, int(engine.R))
                verified = leader >= 0 and bool(ver[leader])
                v = (leader, verified, commit, applied, reb)
                views[g] = v
            return v

        R = int(engine.R)
        G = int(getattr(engine, "G", 1))
        resolved = []
        for t in pending:
            if t.done:
                resolved.append(t)          # already terminal: prune
                continue
            if not (0 <= t.replica < R and 0 <= t.group < G):
                # a malformed ticket must fail ITSELF, never the
                # finishing (readback) thread via an IndexError below
                self._finish(obs, t, None, False)
                resolved.append(t)
                continue
            if t.step0 < 0:
                t.step0 = now               # patience anchors here
            key = (t.group, t.replica) if sharded else t.replica
            if key in nr or key in rb:
                # a quarantined / repair-held / recovering replica
                # serves nothing — same gate as the KVS read path
                self._finish(obs, t, None, False)
                resolved.append(t)
                continue
            leader, verified, commit, applied, reb = view(t.group)
            lm = self.leases
            if lm is not None and lm.valid(t.group, t.replica) \
                    and int(applied[t.replica]) \
                    >= int(commit[t.replica]):
                self._finish(obs, t, PATH_LEASE, True)
                resolved.append(t)
                continue
            if t.read_index is None and leader >= 0 and verified:
                # the ONE confirmation round: the leader proved its
                # authority on this finished step, so its commit index
                # upper-bounds every write acked before this read
                t.read_index = int(commit[leader]) + reb
            if t.read_index is not None \
                    and int(applied[t.replica]) + reb >= t.read_index:
                self._finish(obs, t, PATH_READ_INDEX, True)
                resolved.append(t)
                continue
            if now - t.step0 > t.patience:
                self._finish(obs, t, None, False)
                resolved.append(t)
        if resolved:
            with self._lock:
                gone = set(id(t) for t in resolved)
                self._q = collections.deque(
                    t for t in self._q if id(t) not in gone)
        return len(resolved)

    def fail_all(self, reason: str = "shutdown") -> int:
        """Fail every still-queued read (run end / driver stop):
        nothing will ever step again, so they must fail, not hang.
        Completion goes through the same first-wins commit as the
        drain, so racing the readback thread is safe."""
        with self._lock:
            pending = list(self._q)
            self._q.clear()
        n = 0
        for t in pending:
            if self._commit(t, "failed", None, None):
                n += 1
        return n

    def status(self) -> dict:
        with self._lock:
            return dict(pending=len(self._q), served=dict(self.served),
                        failed=self.failed,
                        patience_steps=self.patience_steps)


def attach(cluster, *, lease_steps: int = 2, guard_steps: int = 2,
           patience_steps: int = 64,
           renew_trace_every: int = 16) -> LeaseManager:
    """Enable the read path on an engine (SimCluster or
    ShardedCluster, any execution mode): creates the per-group
    :class:`LeaseManager` + :class:`ReadHub` pair and hangs them on
    ``cluster.leases`` / ``cluster.reads`` — the engines' ``finish()``
    observes/drains them from then on. Pure host bookkeeping: compiled
    programs and STEP_CACHE keys are untouched."""
    G = int(getattr(cluster, "G", 1))
    lm = LeaseManager(G, lease_steps=lease_steps,
                      guard_steps=guard_steps,
                      renew_trace_every=renew_trace_every)
    hub = ReadHub(lm, patience_steps=patience_steps)
    cluster.leases = lm
    cluster.reads = hub
    return lm
