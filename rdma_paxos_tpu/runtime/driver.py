"""ClusterDriver — the host polling loop gluing every layer together.

This is the analog of the reference's per-replica libev loop (``polling()``,
``dare_server.c:1004-1125``) plus the proxy callbacks, but driving ALL
replicas of an in-process cluster (the simulation/bring-up topology; the
multi-host deployment runs one driver per host over the same components):

  interposed app ──UDS──▶ ProxyServer ──queue──▶ ClusterDriver.step()
        ▲                                            │ SimCluster (jitted
        │ loopback TCP                               ▼  consensus step)
  ReplayEngine ◀──committed entries──┬── StableStore.append (persist)
                                     └── ack release (leader's blocked app)

Per iteration: drain shim events into leader batches → run the jitted
consensus step → persist newly applied entries → replay remote-origin
entries into local apps → release blocked app threads whose events
committed → run election timers (heartbeat = the step itself).
"""

from __future__ import annotations

import collections
import os
import queue as _queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.consensus.membership import MembershipManager
from rdma_paxos_tpu.consensus.snapshot import (
    install_snapshot, recover_vote, take_snapshot)
from rdma_paxos_tpu.consensus.state import ConfigState, Role
from rdma_paxos_tpu.obs import Observability, trace as obs_trace
from rdma_paxos_tpu.obs.alerts import AlertEngine, default_rules
from rdma_paxos_tpu.obs.health import (
    HealthReporter, make_cluster_snapshot, make_snapshot)
from rdma_paxos_tpu.obs.metrics import (
    BATCH_BUCKETS, LATENCY_BUCKETS_S, LATENCY_BUCKETS_US)
from rdma_paxos_tpu.obs.spans import StepPhaseProfiler, span_trace_id
from rdma_paxos_tpu.obs.tracectx import health_blame as _health_blame
from rdma_paxos_tpu.proxy.proxy import (
    PendingEvent, ProxyServer, ReplayEngine, spec_send_refused_dirty)
from rdma_paxos_tpu.proxy.stablestore import (
    HardState, StableStore, atomic_write)
from rdma_paxos_tpu.runtime.hostpath import plan_segment
from rdma_paxos_tpu.runtime.sim import SimCluster
from rdma_paxos_tpu.runtime.timers import ElectionTimer
from rdma_paxos_tpu.utils.debug import ReplicaLog, StepTimer
from rdma_paxos_tpu.utils.codec import fragment


def conn_origin(conn_id):
    """Origin replica/host encoded in a connection id (scalar
    or elementwise on numpy columns) — the ONE place the
    encoding lives."""
    return conn_id >> 24


class _ReplicaRuntime:
    """Host-side per-replica resources."""

    def __init__(self, idx: int, sock_path: Optional[str],
                 app_port: Optional[int], store_path: Optional[str],
                 on_event, timeout_cfg: TimeoutConfig, seed: int,
                 log_path: Optional[str] = None, obs=None):
        self.idx = idx
        self.log = ReplicaLog(log_path, replica=idx, obs=obs)
        self.proxy = (ProxyServer(sock_path, idx, on_event, obs=obs)
                      if sock_path else None)
        self.app_port = app_port
        self.replay = (ReplayEngine("127.0.0.1", app_port)
                       if app_port else None)
        # a SPECULATIVE app (shim HELLO flag) consumed input that was
        # failed at deposition — its state may have diverged from the
        # committed stream. While dirty: committed entries still persist
        # to the store (the store is the source of truth), but nothing
        # is replayed into the app and new client sessions are severed;
        # the operator restarts the app and calls reset_app().
        self.app_dirty = False
        self.last_sync = 0.0      # cadenced store fdatasync bookkeeping
        self.store = StableStore(store_path) if store_path else None
        # durable (term, voted_term, voted_for) — persisted every step the
        # pair changes, restored by recover_replica (election safety
        # across crashes; rc_replicate_vote/rc_get_replicated_vote analog)
        self.hard = HardState(store_path + ".hs") if store_path else None
        # (event, last_fragment_seq) FIFO awaiting commit — every access
        # must hold the driver lock (link threads append, poll thread pops)
        self.inflight: collections.deque = collections.deque()
        self.submit_seq = 0       # monotone per-fragment sequence; stamped
                                  # into the entry's req_id so ack release
                                  # is exact across leadership churn
        self.replay_cursor = 0    # index into cluster.replayed[idx]
        self.replicated_conns: set = set()   # conns whose events replicate
        self.passthrough_conns: set = set()  # our own replay connections
        self.timer = ElectionTimer(timeout_cfg, seed=seed)
        # false-positive detection for the adaptive timeout (to_adjust_cb
        # analog): if the SAME leader heartbeats again shortly after we
        # fired, the timeout was premature -> widen it
        self.fired_leader = -1
        self.fired_countdown = 0


class ClusterDriver:
    def __init__(self, cfg: LogConfig, n_replicas: int, *,
                 workdir: Optional[str] = None,
                 app_ports: Optional[Sequence[Optional[int]]] = None,
                 timeout_cfg: Optional[TimeoutConfig] = None,
                 group_size: Optional[int] = None,
                 mode: str = "sim", seed: int = 0,
                 auto_evict: bool = False, fail_threshold: int = 100,
                 sync_period: float = 0.05, step_down_steps: int = 50,
                 app_snapshot=None, fanout: str = "gather",
                 obs: Optional[Observability] = None,
                 health_period: float = 0.5, link_model=None,
                 fence: bool = False, audit: bool = False,
                 alert_rules: Optional[Sequence[dict]] = None,
                 alert_period: float = 0.25, pipeline: int = 2,
                 telemetry: bool = False,
                 profile_on_page: float = 0.0,
                 repair: bool = False,
                 repair_opts: Optional[Dict] = None,
                 leases: bool = True,
                 lease_opts: Optional[Dict] = None,
                 series_capacity: int = 1280,
                 metrics_port: Optional[int] = None,
                 scan: bool = False,
                 txn: bool = False,
                 governor: bool = False,
                 governor_opts: Optional[Dict] = None,
                 idle_quiesce: bool = True,
                 idle_backoff_max: float = 0.05,
                 streams: bool = False,
                 streams_opts: Optional[Dict] = None):
        self.cfg = cfg
        # scan=True engages the engine's device-resident K-window scan
        # tier on the burst path: one consolidated minimal readback
        # (scalars + in-dispatch replay rows) per K fused steps. The
        # flag lives on the cluster and is runtime-mutable
        # (driver.cluster.scan) — the host_path A/B flips it between
        # rounds; scan-off runs compile no scan programs.
        self._scan = bool(scan)
        # txn=True compiles the transaction vote-lane step variants
        # (txn/lane.py) so a coordinator can be attached
        # (txn.attach_coordinator over a ShardedKVS on this cluster);
        # txn=False programs and cache keys are bit-identical to the
        # unflagged world (tests/test_txn.py pins it)
        self._txn_flag = bool(txn)
        self.sync_period = sync_period
        self._workdir = workdir
        # observability: one registry + trace ring + span recorder per
        # driver (isolated by default — pass a shared facade to
        # aggregate across drivers). ALL instrumentation is host-side:
        # nothing below may run inside jitted code, and tests verify
        # compiled-step cache keys are unchanged by it.
        self.obs = obs if obs is not None else Observability()
        self._timer_obs = StepTimer(metrics=self.obs.metrics)
        # step-phase wall-time attribution (obs.spans profiler). fence
        # keeps its default (False) in production: fencing blocks on
        # the step's outputs right after dispatch so device time lands
        # in its own device_sync histogram — a profiling mode that
        # serializes the dispatch pipeline, never the serving default.
        self._phase_prof = StepPhaseProfiler(metrics=self.obs.metrics,
                                             fence=fence)
        self._health = (HealthReporter(workdir, period=health_period)
                        if workdir else None)
        # bounded recovery: optional app-level snapshot hook tuple
        # (dump_fn(sock)->bytes, restore_fn(sock, blob)[, probe_fn(sock)])
        # speaking the app's own protocol over a passthrough connection.
        # With it, checkpoint_app() captures a follower's app state at a
        # known store index and COMPACTS the store prefix it covers, so
        # donor transfer and fresh-app rebuild become O(app state +
        # suffix) instead of O(entire history) — exceeding the
        # reference, whose snapshot is always the full BDB record stream
        # (db-interface.c:98-134). probe_fn is the EXACT processed-input
        # barrier (request/response roundtrip on a replay connection,
        # returning once its own reply is observed); without it the
        # checkpoint falls back to kernel-queue quiescence, which can
        # still race an app that parks bytes in userspace buffers — see
        # ReplayEngine.quiesce. Supply probe_fn whenever the app's
        # protocol allows one.
        self.app_snapshot = app_snapshot
        # guarded-by: _lock [writes]
        self._ckpt_req: Optional[Tuple[int, threading.Event, list]] = None
        # lost-majority step-down (the reference leader SUICIDES after
        # failing to reach a majority, dare_server.c:1213-1217): a
        # leader whose leadership_verified stays 0 for this many
        # consecutive steps stops SERVING — inflight commits are failed
        # and replicated sessions severed/refused — so a minority-side
        # leader's clients retry against the majority instead of
        # hanging. Unlike the reference's process exit, service resumes
        # if the leader re-verifies (majority restored with no rival).
        self.step_down_steps = step_down_steps
        self.unverified = np.zeros(n_replicas, np.int64)
        self.stepped_down: set = set()
        self.R = n_replicas
        # fanout="psum" is the production full-connectivity
        # configuration (O(W) fan-out); the default stays "gather" so
        # tests can model partitions (see replica_step's docstring)
        # audit=True compiles the digest-chain step variants and runs
        # the cluster AuditLedger + flight recorder (obs/audit.py):
        # continuous proof that all R replicas hold bit-identical
        # committed state, with a bounded evidence ring dumped when
        # the digest-mismatch page fires
        # telemetry=True compiles the device-counter step variants
        # (obs/device.py): protocol counts as the DEVICE saw them,
        # ingested on the readback thread into device_* series — the
        # signals the telemetry-backed alert rules read
        self._telemetry = telemetry
        self.cluster = self._make_cluster(cfg, n_replicas, group_size,
                                          mode, fanout, audit, telemetry,
                                          self._txn_flag)
        self.cluster.obs = self.obs
        self.cluster.profiler = self._phase_prof
        # read scaling (runtime/reads.py): step-domain leader leases
        # renewed by the verified-quorum outputs every step already
        # carries, plus the queued read hub drained on the readback
        # thread between pipelined tickets. Host bookkeeping only —
        # reads never enter begin_*/finish, never consume ring slots,
        # never change a STEP_CACHE key.
        if leases:
            from rdma_paxos_tpu.runtime import reads as _reads
            _reads.attach(self.cluster, **(lease_opts or {}))
        # log-as-product streams (streams/): ordered range scans,
        # watch/subscribe with exactly-once resume, CDC export — one
        # tail-follower over the committed replay streams, observed at
        # the finish() tail. Host-side only: zero device changes, zero
        # new STEP_CACHE keys (tests/test_streams.py pins it). A
        # workdir defaults the CDC sink to <workdir>/cdc.jsonl when
        # streams_opts doesn't name one.
        self.streams = None
        if streams:
            from rdma_paxos_tpu import streams as _streams
            sopts = dict(streams_opts or {})
            if workdir and "cdc_path" not in sopts:
                sopts["cdc_path"] = os.path.join(workdir, "cdc.jsonl")
            if audit and "auditor" not in sopts:
                sopts["auditor"] = getattr(self.cluster, "auditor",
                                           None)
            self.streams = _streams.attach(self.cluster, obs=self.obs,
                                           **sopts)
        # time-series retention (obs/series.py): the registry sampled
        # into bounded per-series rings on the alert cadence — the
        # substrate the window-domain rules (rate_window / burn_rate)
        # and the /series endpoint read. With a workdir the samples
        # persist as append-only JSONL (cross-host merge = file
        # concat). Host bookkeeping only: no compiled program or
        # STEP_CACHE key changes (tests/test_ops_plane.py pins it).
        # Capacity must cover the LONGEST rule window at this cadence
        # (default 1280 x 0.25 s = 320 s > the 300 s slow burn
        # window) — a shorter ring saturates early and the slow
        # window degrades to full-retention, weakening the
        # multi-window transient suppression.
        from rdma_paxos_tpu.obs.series import TimeSeriesStore
        self.series = TimeSeriesStore(
            capacity=series_capacity,
            path=(os.path.join(workdir, "series.jsonl")
                  if workdir else None),
            source="driver")
        # SLO alert rules (obs/alerts.py) evaluated on a cadence from
        # the poll loop; firing state rides health snapshots and the
        # alert_firing{alert=...} gauges
        self.alerts = AlertEngine(
            self.obs.metrics,
            rules=(alert_rules if alert_rules is not None
                   else default_rules()),
            trace=self.obs.trace, series=self.series)
        self._alert_period = alert_period
        self._alert_last = float("-inf")
        self.exporter = None
        self._metrics_port = metrics_port
        self.audit_artifact: Optional[str] = None
        # self-healing (runtime/repair.py): repair=True closes the
        # audit loop — DIVERGENCE → quarantine → digest-verified
        # snapshot re-install from a ledger-majority donor →
        # range-digest backfill → probation re-admit. observe() runs
        # per finished step (readback thread); the state surgery runs
        # only on drained serial iterations (_drain_admin →
        # repair.drive; _pipeline_ready defers while a repair is due).
        self.repair = None
        if repair:
            if not audit:
                raise ValueError("repair=True requires audit=True "
                                 "(the ledger drives donor selection "
                                 "and install verification)")
            from rdma_paxos_tpu.runtime.repair import RepairController
            self.repair = RepairController(self.cluster, obs=self.obs,
                                           **(repair_opts or {}))
            self._wire_repair()
            self.alerts.add_hook(self.repair.on_alert)
        # adaptive dispatch governor (runtime/governor.py): a
        # step-domain feedback controller on the readback thread that
        # picks the dispatch tier (serial / burst K / scan K from the
        # prewarmed ladder), engages/disengages pipelining, and
        # applies a bounded admission-coalescing wait — and sheds to
        # serial the moment the commit-latency burn-rate pager fires
        # (AlertEngine.add_hook, the RepairController.on_alert
        # pattern), so it is a pure throughput win that can never
        # page the latency SLO. Host bookkeeping only: zero new
        # STEP_CACHE keys (tests/test_governor.py pins it).
        self.governor = None
        if governor:
            from rdma_paxos_tpu.runtime.governor import attach_governor
            self.governor = attach_governor(
                self.cluster, obs=self.obs, alerts=self.alerts,
                **(governor_opts or {}))
            self.alerts.add_hook(self.governor.on_alert)
        # idle quiescence: when there is no standing backlog, no
        # blocked waiter, no election timer anywhere near due, and no
        # admin/repair/config work, the poll loop SKIPS the device
        # dispatch entirely and parks with an exponential backoff —
        # instead of free-running heartbeat steps that burn the shared
        # core the app needs (the PR 8 idle-dispatch bias, closed at
        # the source). The alert/health cadences keep running while
        # parked, and any intake event wakes the loop instantly.
        self._idle_quiesce = bool(idle_quiesce)
        self._idle_backoff_max = float(idle_backoff_max)
        self._idle_backoff = 0.001
        self._idle_guard = (timeout_cfg.elec_timeout_low * 0.25
                            if timeout_cfg is not None else 0.025)
        # bounded jax.profiler captures (obs/device.py:ProfilerSession):
        # started via start_profile() (operator / bench CLI) or
        # automatically on the first page-severity alert when
        # profile_on_page > 0 (the capture duration in seconds); the
        # observe pass enforces the bound so an alert-triggered capture
        # can never run unbounded
        self.profile_session = None
        self._profile_on_page = float(profile_on_page)
        self._page_profiled = False
        # chaos hook: a per-link fault model (chaos.faults.LinkModel)
        # driven from outside the poll loop — fault-injection drills
        # against a LIVE driver (apps + stores + poll thread), not just
        # the bare sim. Host-side data rewrite only; with fanout="psum"
        # any non-full mask is rejected by the step, so chaos drills
        # require the default "gather".
        if link_model is not None:
            link_model.obs = self.obs
            self.cluster.link_model = link_model
        # absolute (rebase-corrected) commit cursor per replica, for the
        # committed_entries_total counters / commit_advance traces
        self._prev_commit_abs = np.zeros(n_replicas, np.int64)
        self.timeout_cfg = timeout_cfg or TimeoutConfig()
        # failure detection / eviction (check_failure_count analog):
        # consecutive steps each member failed to ack the leader's window
        self.auto_evict = auto_evict
        self.fail_threshold = fail_threshold
        self.fail_count = np.zeros(n_replicas, np.int64)
        self._mm = MembershipManager(self.cluster)
        # last known membership view (device-state reads are unsafe —
        # and pipeline-serializing — while dispatches are in flight;
        # see _member_view_cached)
        self._member_cur = dict(bitmask_new=(1 << n_replicas) - 1,
                                epoch=0, cid_state=0)
        # (phase, new_mask, epoch, steps_left) — steps_left bounds a change
        # wedged by leader churn losing the CONFIG entry; on expiry the
        # phase resets so eviction/request can be re-issued
        self._config_phase: Optional[Tuple[str, int, int, int]] = None
        self.config_changes_abandoned = 0
        # recovery requests execute inside the poll loop (never racing
        # the stepping thread over cluster.state): (replica, donor,
        # done_event, exception_box) — failures surface to the caller,
        # never kill the loop
        # guarded-by: _lock [writes]
        self._recover_req = None
        # app-reset requests (mis-speculation quarantine exit), same
        # poll-loop execution discipline: (replica, done_event, box)
        # guarded-by: _lock [writes]
        self._reset_req = None
        self._lock = threading.Lock()
        # per-replica queues of (etype, conn_id, fragment_bytes, seq)
        self._submitq: List[List[Tuple[int, int, bytes, int]]]
        self._submitq = [[] for _ in range(n_replicas)]  # guarded-by: _lock
        # advisory leader view: written under the lock on the readback
        # thread; lock-free reads (poll/app threads) tolerate one step
        # of staleness by design  # guarded-by: _lock [writes]
        self._leader_view = -1
        # stores consume the vectorized frame stream from the decode
        self.cluster.collect_frames = workdir is not None
        self.runtimes: List[_ReplicaRuntime] = []
        for r in range(n_replicas):
            sock = (os.path.join(workdir, f"proxy{r}.sock")
                    if workdir else None)
            store = (os.path.join(workdir, f"replica{r}.db")
                     if workdir else None)
            port = app_ports[r] if app_ports else None
            logp = (os.path.join(workdir, f"replica{r}.log")
                    if workdir else None)
            self.runtimes.append(_ReplicaRuntime(
                r, sock, port, store,
                self._make_handler(r), self.timeout_cfg, seed + r,
                log_path=logp, obs=self.obs))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.loop_error: Optional[BaseException] = None
        # event-driven stepping: link threads set this when work arrives
        # so an idle loop wakes instantly instead of polling — on a
        # shared-core host a free-running loop would steal the CPU the
        # app itself needs (the reference's libev loop is fd-driven for
        # the same reason, dare_server.c:1004-1125)
        self._wake = threading.Event()
        # pipelined dispatch (the perf hot path): with pipeline >= 2 the
        # run loop keeps up to ``pipeline`` device dispatches in flight
        # — the dispatch thread encodes batch k+1 while batch k runs on
        # the device, and a dedicated READBACK thread blocks on outputs
        # and runs all post-step host work (requeue, replay, acks,
        # observability), so device_sync never serializes the enqueue
        # path. Election timeouts, admin requests (recover/reset/ckpt),
        # rebase drains, and recovery always drain the pipeline first
        # and run through the serial step() — pipelining is engaged
        # only on the stable-leader traffic path, where it is a pure
        # latency/throughput transform (the commit stream and ack
        # stream are bit-identical to the serial driver; tests pin it).
        # Mutable at runtime (A/B benches flip it between rounds).
        self.pipeline = max(int(pipeline), 0)
        self._pl_cv = threading.Condition()
        self._pl_pending = 0        # dispatched, not yet post-stepped
        self._pl_queue: _queue.Queue = _queue.Queue()
        self._rb_thread: Optional[threading.Thread] = None
        # opt-in ops exporter (obs/export.py): /metrics /healthz
        # /series /alerts on a localhost port (0 = ephemeral) — runs
        # beside the readback thread, never on the dispatch path.
        # Attached LAST: a scrape may land the instant the socket
        # binds, and health() touches everything above.
        if self._metrics_port is not None:
            self.serve_metrics(self._metrics_port)

    def _make_cluster(self, cfg, n_replicas, group_size, mode, fanout,
                      audit, telemetry, txn=False):
        """Engine factory (the sharded driver subclass overrides this
        to serve a multi-group ShardedCluster through the same loop)."""
        return SimCluster(cfg, n_replicas, group_size, mode=mode,
                          fanout=fanout, audit=audit,
                          telemetry=telemetry, scan=self._scan,
                          txn=txn)

    def _wire_repair(self) -> None:
        """Single-group driver: repair installs ride
        :meth:`_do_recover` (store transfer + live-app delta replay
        included) with the ledger passed through, so the install is
        digest-verified end to end and a corrupted donor raises into
        the controller's donor-retry loop."""
        self.repair.install_hook = self._repair_install

    def _repair_install(self, g: int, r: int, donor: int) -> None:
        self._do_recover(r, donor, app_fresh=False,
                         ledger=self.repair.led,
                         min_verified=self.repair.min_verified)
        # the device log + store are now healed from a digest-verified
        # donor, but a LIVE interposed app may already have executed
        # bytes the corruption reached before detection — its state
        # cannot be trusted either way (the audit cannot tell pre- from
        # post-replay corruption). Quarantine it through the existing
        # mis-speculation machinery: the store keeps persisting, and
        # the operator restarts the app + reset_app() rebuilds it from
        # the healed store. Consensus-level re-admission (leadership,
        # replication, audit coverage) completes automatically.
        rt = self.runtimes[r]
        if rt.replay is not None and not rt.app_dirty:
            rt.app_dirty = True
            rt.log.info_wtime(
                "REPAIR: app quarantined pending reset_app (its state "
                "may derive from corrupted committed bytes)")

    def _repair_blocked(self, r: int, group: int = 0) -> bool:
        return (self.repair is not None
                and self.repair.serving_blocked(group, r))

    # ------------------------------------------------------------------
    # shim event intake (called from proxy link threads)
    # ------------------------------------------------------------------

    def _make_handler(self, r: int):
        def on_event(etype: int, conn_id: int, payload: bytes):
            """Returns None (pass through), an int status (<0 severs the
            connection), or a PendingEvent (block until committed)."""
            with self._lock:
                rt = self.runtimes[r]

                def refuse_send():
                    """Refuse with -1, quarantining a speculative app
                    whose delivered bytes this refusal strands (shared
                    policy: proxy.spec_send_refused_dirty)."""
                    if spec_send_refused_dirty(
                            etype, conn_id, rt.replicated_conns,
                            rt.proxy, rt.app_dirty):
                        rt.app_dirty = True
                        rt.log.info_wtime(
                            "APP DIRTY: speculated SEND refused at "
                            "intake (conn %d)" % conn_id)
                    self.obs.metrics.inc("events_refused_total",
                                         replica=r)
                    return -1

                if self.loop_error is not None or self._stop.is_set():
                    # no poll loop will ever release a commit wait: fail
                    # fast so the app severs and the client retries
                    return refuse_send()
                if etype == int(EntryType.CONNECT):
                    # our own replay connections (recognized by peer port)
                    # stay local; so do client connections on non-leaders
                    # (stale local reads — the reference's followers serve
                    # the same way, proxy.c:230-239 is_leader gate)
                    port = (int.from_bytes(payload[4:6], "big")
                            if len(payload) >= 6 else 0)
                    if (rt.replay is not None
                            and port in rt.replay.local_ports):
                        rt.passthrough_conns.add(conn_id)
                        return None
                    if rt.app_dirty:
                        # a dirty (mis-speculated) app must not serve
                        # clients — not even stale local reads
                        return -1
                    if not self._accepts_clients(r):
                        return None
                    if r in self.stepped_down:
                        # a stepped-down (majority-less) leader accepts
                        # no new sessions at all — the reference's
                        # suicided leader serves nothing
                        return -1
                    rt.replicated_conns.add(conn_id)
                    payload = b""
                elif conn_id in rt.passthrough_conns:
                    if etype == int(EntryType.CLOSE):
                        rt.passthrough_conns.discard(conn_id)
                    return None
                elif conn_id not in rt.replicated_conns:
                    return None          # never-replicated local session
                elif r in self.stepped_down:
                    # lost-majority step-down: refuse replicated service
                    # (a commit wait could never complete)
                    status = refuse_send()
                    rt.replicated_conns.discard(conn_id)
                    return status
                elif rt.app_dirty:
                    # a surviving replicated session on a replica whose
                    # app diverged (mis-speculation) must be severed
                    # even if this replica regained leadership — its
                    # replies would come from state that does not match
                    # the committed stream
                    rt.replicated_conns.discard(conn_id)
                    return -1
                elif not self._accepts_clients(r):
                    # a REPLICATED session must never silently downgrade
                    # to unreplicated service after deposition: sever it
                    # so the client reconnects to the current leader
                    if etype == int(EntryType.CLOSE):
                        rt.replicated_conns.discard(conn_id)
                        return None
                    return refuse_send()
                if etype == int(EntryType.CLOSE):
                    rt.replicated_conns.discard(conn_id)
                return self._enqueue_locked(r, rt, etype, conn_id,
                                            payload)
        return on_event

    def _accepts_clients(self, r: int) -> bool:
        """Client-session admission: the single-group driver serves
        replicated sessions on the leader only (non-leaders give stale
        local reads, the reference's follower semantics) — and never a
        replica the repair pipeline holds in quarantine/probation. The
        sharded driver overrides this — every replica is a serving
        front-end demuxing onto the G group leaders."""
        return self._leader_view == r and not self._repair_blocked(r)

    def _enqueue_locked(self, r: int, rt: _ReplicaRuntime, etype: int,
                        conn_id: int, payload: bytes):
        """Admit one gate-passed replicated event: fragment, stamp
        sequence numbers, queue for the next dispatch, and park the
        blocked app thread's PendingEvent (caller holds ``_lock``).
        The sharded driver overrides this to pin the connection to its
        key-routed consensus group first."""
        frags = (fragment(payload, self.cfg.slot_bytes)
                 if etype == int(EntryType.SEND) else [payload])
        ev = PendingEvent(EntryType(etype), conn_id, payload)
        for f in frags:
            rt.submit_seq += 1
            self._submitq[r].append((etype, conn_id, f,
                                     rt.submit_seq))
        rt.inflight.append((ev, rt.submit_seq))
        self.obs.metrics.inc("proxy_events_total", replica=r)
        self.obs.trace.record(obs_trace.PROXY_ENQUEUE,
                              replica=r, etype=etype,
                              conn=conn_id, frags=len(frags),
                              submit_seq=rt.submit_seq)
        # causal span birth: keyed (conn, final fragment seq) —
        # the exact pair the ack-release path matches on
        self.obs.spans.begin(conn_id, rt.submit_seq, r)
        self._wake.set()
        return ev

    # ------------------------------------------------------------------
    # the polling loop
    # ------------------------------------------------------------------

    def _drain_admin(self) -> None:
        """Serve pending operator requests (recovery / app reset /
        checkpoint) — they execute on the stepping thread so they never
        race it over cluster state, and only with the dispatch pipeline
        fully drained."""
        # pop each request slot under the lock: the writers
        # (recover_replica / reset_app / checkpoint_app on caller
        # threads) publish under it, and an unlocked clear here could
        # lose a request armed between the read and the None-store
        # (graftlint lock-discipline rider)
        with self._lock:
            req, self._recover_req = self._recover_req, None
        if req is not None:
            r, donor, done, box = req
            try:
                self._do_recover(r, donor)
            except Exception as exc:  # noqa: BLE001 — reported to caller
                box.append(exc)
            finally:
                done.set()
        with self._lock:
            rreq, self._reset_req = self._reset_req, None
        if rreq is not None:
            r, done, box = rreq
            try:
                self._do_reset_app(r)
            except Exception as exc:  # noqa: BLE001 — reported to caller
                box.append(exc)
            finally:
                done.set()
        with self._lock:
            creq, self._ckpt_req = self._ckpt_req, None
        if creq is not None:
            r, done, box = creq
            try:
                self._do_checkpoint(r)
            except Exception as exc:  # noqa: BLE001 — reported to caller
                box.append(exc)
            finally:
                done.set()
        # self-healing: due repairs run HERE — the serial path, after
        # the dispatch loop drained every in-flight ticket (drive()
        # itself defers if anything is still in flight, the same
        # contract _drive_config_change uses)
        if self.repair is not None:
            self.repair.drive()
        # elastic topology: transition passes (seed/freeze/cutover)
        # run on the same drained serial path, after repair (repair
        # gets priority; the window defers or abandons around it)
        topo = getattr(self.cluster, "topology", None)
        if topo is not None:
            topo.drive()

    def _pump_submitq(self) -> None:
        """Move intake rows into the engine's pending queues — ONE
        locked extend per replica (batched intake, no per-entry
        Python). Holds the engine's host lock too: the pipelined
        readback thread requeues ring-full shortfalls into the same
        lists concurrently."""
        with self._lock, self.cluster._host_lock:
            for r in range(self.R):
                q = self._submitq[r]
                if q:
                    self.cluster.submit_many(
                        r, [(etype, conn, seq, frag)
                            for etype, conn, frag, seq in q])
                    q.clear()

    def step(self) -> Dict:
        """One host-loop iteration (public for deterministic tests).
        Serial: dispatch + readback fused — the pipelined run loop
        splits the same work into begin_* on the dispatch thread and
        ``_post_step`` on the readback thread."""
        self._drain_admin()
        self._pump_submitq()

        # a flagged (force-pruned) leader never heals on its own: it
        # acks windows and heartbeats normally, so nothing deposes it,
        # its app/store stay frozen (stale reads), and every other
        # flagged member's recovery starves behind it. The same goes
        # for a leader the repair pipeline holds (quarantine cuts its
        # links, but it keeps self-claiming; probation must not lead
        # either). Actively depose it: fire an election timeout on a
        # healthy member each step until leadership moves
        # (run_until_elected cadence).
        depose = -1
        lead = self._leader_view
        if (lead >= 0
                and (lead in self.cluster.need_recovery
                     or self._repair_blocked(lead))):
            mask = self._mm.current(lead)["bitmask_new"]
            healthy = [r for r in range(self.R)
                       if (mask >> r) & 1 and r != lead
                       and r not in self.cluster.need_recovery
                       and not self._repair_blocked(r)]
            if healthy:
                depose = min(healthy)

        # pending work + known leader: drain through a multi-step burst
        # (one dispatch fuses up to K_TIERS[-1] protocol steps; no
        # election timeouts can fire inside — each burst step carries the
        # heartbeat, so follower timers are beaten right after). Bursts
        # are the DEFAULT e2e path — any backlog rides a fused dispatch;
        # the single-step path serves elections, deposes, and idle
        # heartbeats.
        # governed tier: the governor's decision caps the burst at a
        # lower ladder rung, or routes the iteration through the
        # serial single step entirely (latency-bound regime / SLO
        # shed). Ungoverned drivers keep the auto-sized burst.
        dec = (self.governor.decision if self.governor is not None
               else None)
        if (depose < 0
                and self._leader_view >= 0 and self.cluster.last is not None
                and self._backlog()
                and not (self.cluster.txn is not None
                         and self.cluster.txn.wants_serial())
                and (dec is None or dec.max_k > 1)):
            self._timer_obs.start("device_step")
            res = self.cluster.step_burst(
                max_k=dec.max_k if dec is not None else None)
            self._timer_obs.stop("device_step")
        else:
            timeouts = []
            last = self.cluster.last
            for r, rt in enumerate(self.runtimes):
                if last is not None and last["role"][r] == int(Role.LEADER):
                    continue
                if rt.timer.expired() or r == depose:
                    timeouts.append(r)
                    rt.timer.beat()
                    self.obs.metrics.inc("election_timeouts_total",
                                         replica=r)
                    self.obs.trace.record(
                        obs_trace.ELECTION_START, replica=r,
                        depose=(r == depose),
                        term=(int(last["term"][r])
                              if last is not None else 0))
                    if r != depose:
                        # a deliberate deposition is not a mistimed
                        # timeout: it must not feed the adaptive
                        # false-positive widening (the flagged leader IS
                        # alive and heartbeating)
                        rt.fired_leader = (int(last["leader_id"][r])
                                           if last is not None else -1)
                        rt.fired_countdown = 50
            self._timer_obs.start("device_step")
            res = self.cluster.step(timeouts=timeouts)
            self._timer_obs.stop("device_step")
        return self._post_step(res)

    def _backlog(self) -> int:
        """Entries awaiting dispatch in the engine's pending queues."""
        return max(len(q) for q in self.cluster.pending)

    def _update_leader_view(self, res) -> None:
        with self._lock:
            # multiple self-claimed leaders can coexist transiently (an
            # isolated deposed leader cannot hear the higher term); the
            # real one is the highest-term claimant — terms are unique per
            # leader by quorum election
            claims = [(int(res["term"][r]), r) for r in range(self.R)
                      if res["role"][r] == int(Role.LEADER)]
            self._leader_view = max(claims)[1] if claims else -1

    def _post_step(self, res) -> Dict:
        """Every post-readback host rule for one step's outputs: leader
        view, durable election state, timer beats, store/replay/ack
        release, detectors, recovery drive, and observability export.
        Serial ``step()`` runs it inline; the pipelined loop runs it on
        the READBACK thread, so none of this work — observability
        included — can serialize the dispatch path it measures."""
        self._update_leader_view(res)

        for r, rt in enumerate(self.runtimes):
            if rt.hard is not None:
                rt.hard.save(int(res["term"][r]),
                             int(res["voted_term"][r]),
                             int(res["voted_for"][r]))
            if res["became_leader"][r]:
                rt.log.leader_elected(int(res["term"][r]))
            if res["hb_seen"][r] or res["role"][r] == int(Role.LEADER):
                rt.timer.beat()
            if rt.fired_countdown > 0:
                rt.fired_countdown -= 1
                if (res["hb_seen"][r] and rt.fired_leader >= 0
                        and int(res["leader_id"][r]) == rt.fired_leader):
                    # the leader we timed out on is alive: premature
                    # timeout -> widen adaptively (to_adjust_cb analog)
                    rt.timer.false_positive()
                    rt.fired_countdown = 0
            self._apply_new_entries(r, rt)
            if res["role"][r] != int(Role.LEADER):
                with self._lock:
                    # lost leadership with blocked app threads: fail them
                    # so clients reconnect to the new leader (reference
                    # clients time out the same way). Fragments already
                    # replicated may still commit later; seq-stamped acks
                    # make those late applies harmless no-ops.
                    self._fail_inflight_locked(rt, "deposition")

        self._step_down_detector(res)
        self._failure_detector(res)
        self._drive_config_change()
        # self-healing observation: consume new DIVERGENCE findings
        # (quarantine is host bookkeeping — safe on this, the readback,
        # thread) and advance probation hysteresis; the state surgery
        # itself waits for a drained serial iteration (_drain_admin)
        if self.repair is not None:
            self.repair.observe()
        # a replica force-pruned past its apply cursor (wedged app now
        # unwedged, or long stall) stopped replaying; heal it with a
        # donor snapshot — the reference's straggler-eviction-then-
        # rejoin collapsed into one step (one per iteration). Replicas
        # the repair controller owns are ITS to heal (ledger-verified
        # donor), not this default path's.
        if (self.cluster.need_recovery
                and self._leader_view >= 0
                # never under in-flight dispatches: snapshot install
                # rewrites cluster state the pipeline is still feeding
                # (the dispatch loop sees need_recovery and drains, so
                # the next drained iteration takes this branch)
                and not self.cluster._tickets
                # the donor is the leader: it must itself be healthy —
                # a flagged leader's host store is frozen, so its
                # snapshot would silently drop acked writes; wait for
                # leadership to move to a usable member instead
                and self._leader_view not in self.cluster.need_recovery):
            # never pick the leader itself as the recoveree either (a
            # flagged replica can still win elections — it acks windows
            # regardless of apply); it recovers once deposed, and must
            # not starve the others
            owned = (self.repair.owned() if self.repair is not None
                     else set())
            cands = (self.cluster.need_recovery - {self._leader_view}
                     - owned)
            if cands:
                r = min(cands)
                try:
                    self._do_recover(r, None, app_fresh=False)
                except RuntimeError as exc:
                    # unrecoverable in place (e.g. the donor compacted
                    # past this app's applied prefix): quarantine the
                    # app for an operator restart + reset_app rather
                    # than killing the poll loop or retrying forever
                    rt = self.runtimes[r]
                    rt.app_dirty = True
                    rt.log.info_wtime("AUTO-RECOVERY FAILED: %s" % exc)
                self.cluster.need_recovery.discard(r)
        self._observe_step(res)
        return res

    # ------------------------------------------------------------------
    # observability (host-side only — see rdma_paxos_tpu.obs)
    # ------------------------------------------------------------------

    def _observe_step(self, res) -> None:
        """Export the step's protocol-level signals: per-replica
        role/term/index gauges, rebase headroom against the i32
        ceiling, commit-advance counters + trace, batch-size histogram,
        and the cadenced health snapshot files."""
        m = self.obs.metrics
        rebased = getattr(self.cluster, "rebased_total", 0)
        for r in range(self.R):
            m.set("replica_role", int(res["role"][r]), replica=r)
            m.set("replica_term", int(res["term"][r]), replica=r)
            m.set("commit_index", int(res["commit"][r]), replica=r)
            m.set("apply_index", int(res["apply"][r]), replica=r)
            m.set("end_index", int(res["end"][r]), replica=r)
            m.set("rebase_headroom",
                  self.cfg.rebase_threshold - int(res["end"][r]),
                  replica=r)
            m.set("inflight_waiters", len(self.runtimes[r].inflight),
                  replica=r)
            acc = int(res["accepted"][r])
            if acc > 0:
                m.inc("accepted_entries_total", acc, replica=r)
                m.observe("step_batch_entries", acc,
                          buckets=BATCH_BUCKETS, replica=r)
                self.obs.trace.record(obs_trace.STEP_BATCH, replica=r,
                                      entries=acc)
            commit_abs = int(res["commit"][r]) + rebased
            delta = commit_abs - int(self._prev_commit_abs[r])
            if delta > 0:
                self._prev_commit_abs[r] = commit_abs
                m.inc("committed_entries_total", delta, replica=r)
                self.obs.trace.record(obs_trace.COMMIT_ADVANCE,
                                      replica=r, commit=commit_abs,
                                      delta=delta)
        # cluster-level leader view (the leaderless alert's input)
        m.set("cluster_leader", self._leader_view)
        self._cadence_observe()

    def _cadence_observe(self) -> None:
        """The wall-cadenced observability work (alert evaluation +
        series sampling, profiler expiry, health snapshot files) —
        shared by the per-step observe pass AND the idle-quiescence
        branch, so a parked poll loop keeps its alerts and health
        files fresh while skipping device dispatches."""
        now = time.monotonic()
        if now - self._alert_last >= self._alert_period:
            self._alert_last = now
            self.evaluate_alerts()
        self._poll_profile()
        if self._health is not None and self._health.due():
            try:
                # ONE health() pass feeds both files: the per-replica
                # snapshots and the cluster-level document (leader
                # view, lease/read status, repair state, ALERT firing
                # state — the file-based console's and the postmortem
                # bundle's cluster source)
                h = self.health()
                self._health.write({rep["replica"]: rep
                                    for rep in h["replicas"]})
                self._health.write_cluster(h)
            except OSError:
                # observability I/O must never kill the data path: a
                # vanished workdir / full disk costs the snapshot, not
                # the poll loop (an OSError here would otherwise be
                # treated as a fatal step crash and fail every inflight
                # commit)
                pass

    def _health_snapshots(self, res) -> Dict[int, Dict]:
        """Per-replica health dicts (the obs.health schema plus store /
        rebase extras) — written to ``replica<r>.health.json`` on the
        reporter cadence and aggregated live by :meth:`health`."""
        snaps = {}
        for r in range(self.R):
            rt = self.runtimes[r]
            snaps[r] = make_snapshot(
                replica=r,
                role=int(res["role"][r]),
                term=int(res["term"][r]),
                leader_id=int(res["leader_id"][r]),
                commit=int(res["commit"][r]),
                apply=int(res["apply"][r]),
                end=int(res["end"][r]),
                head=int(res["head"][r]),
                log_headroom=(self.cfg.rebase_threshold
                              - int(res["end"][r])),
                inflight=len(rt.inflight),
                app_dirty=rt.app_dirty,
                stepped_down=r in self.stepped_down,
                need_recovery=r in self.cluster.need_recovery,
                rebases=self.cluster.rebases,
                rebase_stalled=self.cluster.rebase_stalled,
                store=(rt.store.stats() if rt.store is not None
                       else None),
            )
        return snaps

    def evaluate_alerts(self) -> Dict:
        """One SLO-rule evaluation pass (also called on a cadence from
        the poll loop). A newly-firing ``page``-severity alert on an
        audited cluster dumps the audit artifact (ledger + flight ring
        + obs dumps) for post-mortem, and — with ``profile_on_page``
        set — starts ONE bounded device-profiler capture so the pages'
        root cause is inspectable on the device timeline.

        The series store samples FIRST, from the same registry
        snapshot the rules then evaluate — so the window-domain rules
        (rate_window / burn_rate) always see the freshest point and
        the retention cadence IS the alert cadence."""
        snap = self.obs.metrics.snapshot()
        if self.series is not None:
            self.series.sample(snap,
                               step=int(self.cluster.step_index))
        out = self.alerts.evaluate(snap=snap)
        pages = [n for n in out["fired"]
                 if self.alerts.severity(n) == "page"]
        if pages and (self.cluster.auditor is not None
                      or self.cluster.flight is not None):
            self._dump_audit_artifact("alert: " + ",".join(pages))
        if (pages and self._profile_on_page > 0
                and not self._page_profiled):
            self._page_profiled = True      # one capture per process
            try:
                self.start_profile(seconds=self._profile_on_page)
                self.obs.trace.record(obs_trace.ALERT_FIRED,
                                      alert="profile_capture",
                                      severity="info",
                                      value=",".join(pages))
            except RuntimeError:
                pass        # another capture is active — keep serving
        return out

    # ------------------------------------------------------------------
    # bounded device-profiler captures (obs/device.py:ProfilerSession)
    # ------------------------------------------------------------------

    def start_profile(self, seconds: float = 5.0,
                      log_dir: Optional[str] = None):
        """Begin a bounded ``jax.profiler`` capture of the serving
        path; the poll loop stops it when ``seconds`` elapse (or call
        :meth:`stop_profile`). The capture's Chrome trace merges onto
        the span timeline via ``obs.device.merge_timeline``."""
        from rdma_paxos_tpu.obs.device import ProfilerSession
        if self.profile_session is not None \
                and self.profile_session.active:
            raise RuntimeError("a profiler capture is already active")
        if log_dir is None:
            import tempfile
            log_dir = (os.path.join(self._workdir, "profile")
                       if self._workdir else
                       tempfile.mkdtemp(prefix="rp_profile_"))
        self.profile_session = ProfilerSession(
            log_dir, max_seconds=seconds).start()
        return self.profile_session

    def stop_profile(self):
        """Stop the active capture (idempotent); returns the session
        (trace files resolved) or None when none was started."""
        if self.profile_session is not None:
            self.profile_session.stop()
        return self.profile_session

    def _poll_profile(self) -> None:
        """Observe-pass hook: expire a bounded capture. Profiler I/O
        must never kill the data path."""
        s = self.profile_session
        if s is not None and s.active:
            try:
                s.maybe_stop()
            except Exception:  # noqa: BLE001 — evidence, not data path
                pass    # stop() already marked the session inactive

    def _dump_audit_artifact(self, reason: str) -> Optional[str]:
        from rdma_paxos_tpu.obs.audit import write_audit_artifact
        path = (os.path.join(self._workdir, "audit_dump.json")
                if self._workdir else None)
        try:
            self.audit_artifact = write_audit_artifact(
                path, reason=reason, ledger=self.cluster.auditor,
                flight=self.cluster.flight, obs=self.obs,
                config=dict(n_replicas=self.R,
                            n_slots=self.cfg.n_slots,
                            slot_bytes=self.cfg.slot_bytes,
                            window_slots=self.cfg.window_slots))
        except OSError:
            # evidence I/O must never kill the data path
            return None
        self.obs.trace.record(obs_trace.AUDIT_DUMPED, reason=reason,
                              path=self.audit_artifact)
        return self.audit_artifact

    def health(self) -> Dict:
        """Aggregated cluster health (live — not from the files): the
        per-replica snapshots plus the cluster-level view, conforming
        to ``obs.health.CLUSTER_HEALTH_FIELDS`` (validate with
        ``obs.health.validate_cluster``). Safe to call from any
        thread; uses the last completed step's outputs."""
        res = self.cluster.last
        replicas = (self._health_snapshots(res) if res is not None
                    else {})
        return make_cluster_snapshot(
            leader=self.leader(),
            n_replicas=self.R,
            replicas=[replicas[r] for r in sorted(replicas)],
            rebases=self.cluster.rebases,
            rebase_stalled=self.cluster.rebase_stalled,
            loop_error=(repr(self.loop_error)
                        if self.loop_error else None),
            audit=(self.cluster.auditor.summary()
                   if self.cluster.auditor is not None else None),
            alerts=self.alerts.state(),
            audit_artifact=self.audit_artifact,
            repair=(self.repair.status()
                    if self.repair is not None else None),
            leases=(self.cluster.leases.status()
                    if self.cluster.leases is not None else None),
            reads=(self.cluster.reads.status()
                   if self.cluster.reads is not None else None),
            streams=(self.cluster.streams.status()
                     if self.cluster.streams is not None else None),
            governor=(self.governor.status()
                      if self.governor is not None else None),
            txn=(self.cluster.txn.health()
                 if self.cluster.txn is not None else None),
            blame=_health_blame(self.obs),
        )

    # ------------------------------------------------------------------
    # the ops exporter (obs/export.py) — /metrics /healthz /series
    # /alerts beside the readback thread, never on the dispatch path
    # ------------------------------------------------------------------

    def serve_metrics(self, port: int = 0):
        """Start (or return) the opt-in localhost ops exporter:
        ``/metrics`` (Prometheus text), ``/metrics.json``,
        ``/healthz`` (503 on a dead poll loop), ``/series``,
        ``/alerts``. ``port=0`` binds an ephemeral port — read it
        back from ``driver.exporter.port``. Pure host-side serving of
        already-thread-safe read surfaces; programs and STEP_CACHE
        keys are untouched (pinned by test)."""
        if self.exporter is None:
            from rdma_paxos_tpu.obs.export import OpsExporter
            self.exporter = OpsExporter(
                registry=self.obs.metrics, health_fn=self.health,
                alerts=self.alerts, series=self.series,
                port=port).start()
        return self.exporter

    # ------------------------------------------------------------------
    # failure detection + eviction (push-detection analog: WC failures
    # -> fail_count >= threshold -> CONFIG removal, dare_server.c:1189)
    # ------------------------------------------------------------------

    def _fail_inflight_locked(self, rt: _ReplicaRuntime,
                              site: str) -> None:
        """Fail every blocked commit waiter (caller holds the lock). A
        SPECULATIVE app already executed the inputs being failed, so its
        state may have diverged from the committed stream — quarantine
        it (app_dirty) until rebuilt via reset_app."""
        if (rt.inflight and rt.proxy is not None
                and rt.proxy.spec_mode and not rt.app_dirty):
            rt.app_dirty = True
            rt.log.info_wtime(
                "APP DIRTY: %d speculated events failed at %s"
                % (len(rt.inflight), site))
        n = len(rt.inflight)
        while rt.inflight:
            ev, _ = rt.inflight.popleft()
            ev.release(-1)
        if n:
            self.obs.metrics.inc("inflight_failed_total", n,
                                 replica=rt.idx)
            self.obs.trace.record(obs_trace.INFLIGHT_FAILED,
                                  replica=rt.idx, count=n, site=site)
            # close the failed waiters' spans with a terminal failover
            # status — orphaned spans must never leak across leadership
            # churn (nothing will ever ack them)
            self.obs.spans.fail_open(rt.idx)

    def _step_down_detector(self, res) -> None:
        """Lost-majority step-down (dare_server.c:1213-1217 analog): a
        leader that cannot verify its authority against a majority for
        ``step_down_steps`` consecutive steps stops serving — blocked
        commit waiters fail (clients retry elsewhere) and replicated
        sessions are refused until it re-verifies or is deposed."""
        for r in range(self.R):
            is_lead = res["role"][r] == int(Role.LEADER)
            if is_lead and not res["leadership_verified"][r]:
                self.unverified[r] += 1
            else:
                self.unverified[r] = 0
                if r in self.stepped_down:
                    self.stepped_down.discard(r)
                    self.runtimes[r].log.info_wtime(
                        "REJOINED: leadership re-verified or deposed")
            if (is_lead and r not in self.stepped_down
                    and self.unverified[r] >= self.step_down_steps):
                self.stepped_down.add(r)
                rt = self.runtimes[r]
                # a majority-less leader must not serve lease reads
                # either: revoke before the serving gates react
                if self.cluster.leases is not None:
                    self.cluster.leases.revoke_all(r, "step_down")
                self.obs.metrics.inc("step_downs_total", replica=r)
                self.obs.trace.record(obs_trace.STEP_DOWN, replica=r,
                                      term=int(res["term"][r]),
                                      unverified=int(self.unverified[r]))
                rt.log.info_wtime(
                    "[T%d] LOST MAJORITY: stepping down after %d "
                    "unverified steps" % (int(res["term"][r]),
                                          int(self.unverified[r])))
                # replicated_conns is deliberately NOT cleared: removing
                # a session from the set would downgrade its next event
                # to unreplicated pass-through (acked lost write); the
                # stepped_down branch in on_event severs each surviving
                # session on its next event instead.
                with self._lock:
                    self._fail_inflight_locked(rt, "step-down")

    def _member_view_cached(self, lead: int) -> dict:
        """The current config view (bitmask/epoch/cid_state), refreshed
        from device state only while NOTHING is in flight (a device
        read under in-flight dispatches both races state donation and
        serializes the pipeline). Config changes drain the pipeline
        (see _pipeline_ready), so the cache is stale at most for the
        duration of one drained transition."""
        with self.cluster._host_lock:
            if not self.cluster._tickets:
                self._member_cur = self._mm.current(lead)
        return self._member_cur

    def _failure_detector(self, res) -> None:
        lead = self._leader_view
        if lead < 0:
            self.fail_count[:] = 0
            return
        cur = self._member_view_cached(lead)
        mask = cur["bitmask_new"]
        acked = res["peer_acked"][lead]
        for r in range(self.R):
            if not (mask >> r) & 1 or r == lead:
                self.fail_count[r] = 0
                continue
            self.fail_count[r] = 0 if acked[r] else self.fail_count[r] + 1
        if not self.auto_evict or self._config_phase is not None:
            return
        dead = [r for r in range(self.R)
                if (mask >> r) & 1 and self.fail_count[r]
                >= self.fail_threshold]
        if dead:
            new_mask = mask
            for r in dead:
                new_mask &= ~(1 << r)
            # only evict a strict MINORITY: the survivors must form a
            # majority of the current group, else a transient partition
            # of live nodes would permanently shrink fault tolerance
            survivors = bin(new_mask).count("1")
            if survivors > bin(mask).count("1") // 2:
                self._mm.submit_transit(lead, mask, new_mask,
                                        cur["epoch"] + 1)
                self._config_phase = ("transit", new_mask,
                                      cur["epoch"] + 1, 500)
                self.obs.metrics.inc("evictions_total", len(dead))
                self.obs.trace.record(obs_trace.MEMBERSHIP_CHANGE,
                                      phase="evict_transit", dead=dead,
                                      new_mask=new_mask,
                                      epoch=cur["epoch"] + 1)

    def _drive_config_change(self) -> None:
        """Advance a two-phase (joint-consensus) config change one poll
        iteration at a time — the non-blocking version of
        MembershipManager.change for use inside the polling loop."""
        if self._config_phase is None:
            return
        # under pipelining this runs on the readback thread: in-flight
        # dispatches may have donated the device buffers _mm.current
        # reads, and a concurrent batch take would race submit_stable.
        # The engine host lock brackets every dispatch, so holding it
        # with tickets empty proves no donation can land mid-read —
        # and _pipeline_ready sees the phase and drains, so a deferred
        # iteration drives the change serially (TTL untouched).
        with self.cluster._host_lock:
            if self.cluster._tickets:
                return
            phase, new_mask, epoch, ttl = self._config_phase
            if ttl <= 0:
                # CONFIG entry lost (e.g. leader deposed before it
                # replicated): abandon so the failure detector /
                # operator can resubmit
                self._config_phase = None
                self.config_changes_abandoned += 1
                self.obs.metrics.inc("config_changes_abandoned_total")
                self.obs.trace.record(obs_trace.MEMBERSHIP_CHANGE,
                                      phase="abandoned",
                                      new_mask=new_mask, epoch=epoch)
                return
            self._config_phase = (phase, new_mask, epoch, ttl - 1)
            lead = self._leader_view
            if lead < 0:
                return
            cur = self._mm.current(lead)
            last = self.cluster.last
            committed = (last is not None and
                         int(last["commit"][lead])
                         >= int(last["end"][lead]))
            if phase == "transit":
                if (cur["epoch"] >= epoch
                        and cur["cid_state"] == int(ConfigState.TRANSIT)
                        and committed):
                    self._mm.submit_stable(lead, new_mask, epoch + 1)
                    self._config_phase = ("stable", new_mask,
                                          epoch + 1, ttl)
                    self.obs.trace.record(obs_trace.MEMBERSHIP_CHANGE,
                                          phase="stable_submitted",
                                          new_mask=new_mask,
                                          epoch=epoch + 1)
            elif phase == "stable":
                if (cur["epoch"] >= epoch
                        and cur["cid_state"] == int(ConfigState.STABLE)):
                    self._config_phase = None
                    self.obs.metrics.inc("config_changes_total")
                    self.obs.trace.record(obs_trace.MEMBERSHIP_CHANGE,
                                          phase="complete",
                                          new_mask=new_mask, epoch=epoch)

    def request_membership(self, new_mask: int) -> None:
        """Operator API: start a two-phase change to ``new_mask`` (join /
        upsize / downsize); the polling loop drives it to completion."""
        lead = self._leader_view
        if lead < 0:
            raise RuntimeError("no leader")
        cur = self._mm.current(lead)
        self._mm.submit_transit(lead, cur["bitmask_new"], new_mask,
                                cur["epoch"] + 1)
        self._config_phase = ("transit", new_mask, cur["epoch"] + 1, 500)
        self.obs.trace.record(obs_trace.MEMBERSHIP_CHANGE,
                              phase="transit_requested",
                              new_mask=new_mask, epoch=cur["epoch"] + 1)

    def recover_replica(self, r: int, donor: Optional[int] = None,
                        timeout: float = 60.0) -> None:
        """Snapshot-recover replica ``r`` from ``donor`` (default: current
        leader): install the consensus determinant and transfer the event
        history into r's stable store (reset first — never duplicated).
        The app instance behind r must be fresh (restarted) — its state is
        rebuilt by replaying the store. Executes inside the poll loop so
        it never races the stepping thread over cluster state."""
        done = threading.Event()
        box: list = []
        with self._lock:
            if self._recover_req is not None:
                raise RuntimeError("a recovery request is already pending")
            self._recover_req = (r, donor, done, box)
        self._wake.set()
        if self._thread is None or not self._thread.is_alive():
            self.step()
        elif not done.wait(timeout):
            raise TimeoutError("recovery did not run (loop stalled?)")
        if box:
            raise box[0]

    def reset_app(self, r: int, timeout: float = 60.0) -> None:
        """Exit mis-speculation quarantine: the operator has restarted
        replica ``r``'s app FRESH; rebuild its state by replaying r's own
        committed store (complete — persistence continued while dirty)
        and resume live replay. Executes inside the poll loop."""
        done = threading.Event()
        box: list = []
        with self._lock:
            if self._reset_req is not None:
                raise RuntimeError("an app reset is already pending")
            self._reset_req = (r, done, box)
        self._wake.set()
        if self._thread is None or not self._thread.is_alive():
            self.step()
        elif not done.wait(timeout):
            raise TimeoutError("app reset did not run (loop stalled?)")
        if box:
            raise box[0]

    def _ckpt_path(self, r: int) -> Optional[str]:
        if self._workdir is None:
            return None
        return os.path.join(self._workdir, f"replica{r}.ckpt")

    def _read_ckpt(self, r: int):
        """-> (index, blob) of replica ``r``'s app checkpoint, or None."""
        path = self._ckpt_path(r)
        if path is None or not os.path.exists(path):
            return None
        import struct
        with open(path, "rb") as f:
            raw = f.read()
        if len(raw) < 8:
            return None
        return struct.unpack("<Q", raw[:8])[0], raw[8:]

    def checkpoint_app(self, r: int, timeout: float = 60.0) -> None:
        """Capture replica ``r``'s app state (follower only — a
        speculative leader's app runs AHEAD of commit) at its current
        store index, persist it, and compact the store prefix it covers.
        Executes inside the poll loop so the app/store pair is frozen at
        a consistent point."""
        done = threading.Event()
        box: list = []
        with self._lock:
            if self._ckpt_req is not None:
                raise RuntimeError("a checkpoint is already pending")
            self._ckpt_req = (r, done, box)
        self._wake.set()
        if self._thread is None or not self._thread.is_alive():
            self.step()
        elif not done.wait(timeout):
            raise TimeoutError("checkpoint did not run (loop stalled?)")
        if box:
            raise box[0]

    def _do_checkpoint(self, r: int) -> None:
        import struct
        rt = self.runtimes[r]
        if self.app_snapshot is None:
            raise RuntimeError("no app_snapshot hook configured")
        if rt.replay is None or rt.store is None:
            raise RuntimeError("replica has no app/store")
        if self._leader_view == r:
            raise RuntimeError(
                "checkpoint must come from a follower: a speculative "
                "leader's app state runs ahead of commit")
        if rt.app_dirty:
            raise RuntimeError("cannot checkpoint a dirty app")
        dump_fn = self.app_snapshot[0]
        probe_fn = (self.app_snapshot[2]
                    if len(self.app_snapshot) > 2 else None)
        # store[base, n) has been DELIVERED to the app's replay sockets
        # by the time we run (same poll-loop sweep), but delivery is not
        # consumption: a single-threaded event-loop app may service the
        # dump connection before draining replay bytes buffered on
        # other connections, and compact(n) would then drop records the
        # checkpoint does not cover. Barrier first: a protocol probe per
        # replay connection when the hook provides one, else kernel
        # queue quiescence (send-q + app rx-q empty).
        n = len(rt.store)
        if probe_fn is not None:
            rt.replay.barrier(probe_fn)
        elif not rt.replay.quiesce():
            raise RuntimeError(
                "app did not consume its replay stream (quiesce "
                "timeout); checkpoint aborted to protect compaction")
        with rt.replay.raw_conn() as s:
            blob = dump_fn(s)
        path = self._ckpt_path(r)
        atomic_write(path, struct.pack("<Q", n) + blob)
        rt.store.compact(n)
        self.obs.metrics.inc("checkpoints_total", replica=r)
        self.obs.trace.record(obs_trace.CHECKPOINT_TAKEN, replica=r,
                              record=n, blob_bytes=len(blob))
        rt.log.info_wtime(
            "CHECKPOINT: app state at record %d (%d bytes); store "
            "compacted" % (n, len(blob)))

    def _restore_ckpt(self, rt: _ReplicaRuntime, ckpt) -> None:
        restore_fn = self.app_snapshot[1]
        with rt.replay.raw_conn() as s:
            restore_fn(s, ckpt[1])

    def _do_reset_app(self, r: int) -> None:
        rt = self.runtimes[r]
        if rt.replay is not None:
            rt.replay.close()
            rt.replay = ReplayEngine("127.0.0.1", rt.app_port)
        if rt.store is not None and rt.replay is not None:
            if rt.store.base > 0:
                # the compacted prefix is covered by this replica's own
                # app checkpoint: restore it, then replay the suffix
                ckpt = self._read_ckpt(r)
                if (ckpt is None or ckpt[0] != rt.store.base
                        or self.app_snapshot is None):
                    raise RuntimeError(
                        "store compacted to %d but no matching app "
                        "checkpoint to rebuild from" % rt.store.base)
                self._restore_ckpt(rt, ckpt)
            from rdma_paxos_tpu.proxy.proxy import replay_store_into
            replay_store_into(rt.store, rt.replay, start=0)
        rt.app_dirty = False
        rt.log.info_wtime("APP RESET: rebuilt from committed store")

    def _do_recover(self, r: int, donor: Optional[int],
                    app_fresh: bool = True, ledger=None,
                    min_verified: int = 1) -> None:
        """``app_fresh=False`` (the auto-recovery path) replays only the
        DELTA of the donor's history into r's still-running app — the
        app already executed its own store's prefix; a full replay would
        double-apply non-idempotent commands. ``ledger`` (the repair
        pipeline) makes the transfer DIGEST-VERIFIED: the snapshot
        carries the donor's audit-chain position and the install
        refuses a donor contradicting the ledger majority — raising
        BEFORE any state (device, store, or app) is touched."""
        donor = self._leader_view if donor is None else donor
        if donor < 0:
            raise RuntimeError("no donor available")
        drt, rrt = self.runtimes[donor], self.runtimes[r]
        blob = drt.store.dump() if drt.store else b""
        # the blob matches the donor's HOST apply counter; the device
        # apply can lag it by one step's echo — snapshot at the host's
        snap = take_snapshot(self.cluster.state, donor, blob,
                             index=int(self.cluster.applied[donor]),
                             digests=ledger is not None,
                             rebased_total=self.cluster.rebased_total)
        # restore election durability: newest vote among live peers'
        # records (read BEFORE install wipes r's rows) and r's HardState
        # file; current term floored at all of them
        vt, vf = recover_vote(self.cluster.state, r)
        hs = rrt.hard.load() if rrt.hard is not None else None
        cur_term = 0
        if hs is not None:
            cur_term = hs[0]
            if hs[1] > vt:
                vt, vf = hs[1], hs[2]
        # state surgery under the engine host lock: recovery runs on
        # drained serial iterations, but the lock makes the invariant
        # local — a concurrent submit/begin_* can never observe the
        # install half-applied (graftlint lock-discipline rider)
        with self.cluster._host_lock:
            self.cluster.state = install_snapshot(
                self.cluster.state, r, snap,
                voted_term=vt, voted_for=vf, cur_term=cur_term,
                ledger=ledger, min_verified=min_verified)
            self.cluster.applied[r] = snap.index
            rt_stream = self.cluster.replayed[r]
            rrt.replay_cursor = len(rt_stream)
            # undrained frames predate the snapshot load: appending
            # them to the freshly loaded store would duplicate history
            self.cluster.frames[r] = []
        if rrt.store is not None and snap.store_blob:
            old_len = len(rrt.store)
            rrt.store.reset()
            rrt.store.load(snap.store_blob)
            base = rrt.store.base
            if base > 0:
                # the donor's store was compacted behind its app
                # checkpoint: carry the checkpoint over so r (and any
                # later reset of r) can cover the missing prefix
                if self.app_snapshot is None:
                    raise RuntimeError(
                        "donor %d store is compacted (base %d) but no "
                        "app_snapshot hook is configured to restore its "
                        "checkpoint" % (donor, base))
                ckpt = self._read_ckpt(donor)
                if ckpt is None or ckpt[0] != base:
                    raise RuntimeError(
                        "donor %d store compacted to %d but no matching "
                        "app checkpoint" % (donor, base))
                import shutil
                if self._ckpt_path(r) is not None:
                    shutil.copyfile(self._ckpt_path(donor),
                                    self._ckpt_path(r))
                if app_fresh:
                    self._restore_ckpt(rrt, ckpt)
                elif old_len < base:
                    raise RuntimeError(
                        "live app executed only %d records but the "
                        "donor history now starts at %d — restart the "
                        "app and use reset_app" % (old_len, base))
            from rdma_paxos_tpu.proxy.proxy import replay_store_into
            # fresh app: rebuild checkpoint + full retained history;
            # live app (auto recovery): deliver only the records beyond
            # the prefix it already executed — its own old store (a
            # prefix of the donor's, both being the committed order)
            replay_store_into(rrt.store, rrt.replay,
                              start=0 if app_fresh else old_len)

    def _apply_new_entries(self, r: int, rt: _ReplicaRuntime) -> None:
        stream = self.cluster.replayed[r]
        n = len(stream)
        if rt.replay_cursor >= n:
            return
        self._phase_prof.start("apply_replay_ack")
        # the engine's decode left the new entries as COLUMNAR batches
        # (hostpath.ReplayBatch): the replay/ack sweep below touches
        # Python O(1) per window, not O(1) per entry
        segs = (stream.segments_from(rt.replay_cursor)
                if hasattr(stream, "segments_from")
                else [stream[rt.replay_cursor:]])
        rt.replay_cursor = n
        if rt.store is not None:
            # frames were assembled vectorized during the window decode
            # (SimCluster.collect_frames); one syscall appends the batch
            blobs = self.cluster.frames[r]
            if blobs:
                self.cluster.frames[r] = []
                for b in blobs:
                    rt.store.append_framed(b)
        # a dirty app's state diverged: keep persisting (the store stays
        # the complete committed stream) but feed the app nothing until
        # reset_app rebuilds it
        replaying = rt.replay is not None and not rt.app_dirty
        own_max = -1
        n_replayed = 0

        def own_of(conns, _gens):
            return conn_origin(conns) == r

        for seg in segs:
            seg_max, ops, n_rem = plan_segment(seg, own_of,
                                               want_ops=replaying)
            own_max = max(own_max, seg_max)
            n_replayed += n_rem
            if replaying:
                # remote SEND runs arrive coalesced per connection
                # (one loopback write per run — byte-stream identical
                # for the app); CONNECT/CLOSE apply individually
                for etype, conn, payload in ops:
                    rt.replay.apply(etype, conn, payload)
        if replaying:
            rt.replay.drain_responses()
        if rt.store is not None:
            # The WRITE precedes the ack (store_record runs inside the
            # reference's apply, before the proxy releases the client,
            # db-interface.c:65-96) — but the reference never fsyncs per
            # record: its durability contract is replication to a
            # QUORUM'S MEMORY plus an OS-buffered store write. Matching
            # that, fdatasync runs on a cadence (and at close/snapshot),
            # not on the ack path — a per-batch fsync was a measurable
            # share of the shared-core budget and bought durability the
            # reference never promised.
            now = time.monotonic()
            if now - rt.last_sync > self.sync_period:
                rt.store.sync()
                rt.last_sync = now
        if replaying and n_replayed:
            self.obs.metrics.inc("replayed_entries_total",
                                 n_replayed, replica=r)
        if own_max >= 0:
            # ack release by sequence: every own-origin entry carries
            # the fragment seq in req_id (monotone in commit order), so
            # commits are matched exactly even across leadership churn
            self._phase_prof.start("ack_release")
            releases = []
            with self._lock:
                while rt.inflight and rt.inflight[0][1] <= own_max:
                    ev, seq = rt.inflight.popleft()
                    releases.append((ev, seq))
            # spans first so the latency observe below can attach the
            # SAMPLED releases' span ids as histogram exemplars
            sampled = {}
            if releases:
                self.obs.trace.record(obs_trace.PROXY_ACK_RELEASE,
                                      replica=r, count=len(releases),
                                      submit_seq=own_max)
                sampled = {req: conn for conn, req
                           in self.obs.spans.ack_release(r, own_max)}
            now = time.perf_counter()
            for ev, seq in releases:
                ev.release(0)
                # intake→release is the client-visible commit latency
                # (the spin at proxy.c:160, measured instead of spun)
                self.obs.metrics.observe(
                    "commit_latency_seconds", now - ev.t0,
                    buckets=LATENCY_BUCKETS_S,
                    exemplar=(span_trace_id(sampled[seq], seq)
                              if seq in sampled else None),
                    replica=r)
            self._phase_prof.stop("ack_release")
        self._phase_prof.stop("apply_replay_ack")

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def _handle_loop_crash(self, exc: BaseException) -> None:
        """A raised step must never silently kill the poll thread with
        app threads parked on commit waits: record it, fail every
        blocked event so the apps sever/retry, and stop the loop."""
        import traceback
        self.loop_error = exc
        traceback.print_exc()
        self.obs.metrics.inc("loop_errors_total")
        with self._lock:
            for rt in self.runtimes:
                self._fail_inflight_locked(rt, "poll-loop crash")
        if self._workdir is not None:
            # post-mortem: persist the protocol trace ring next to the
            # replica logs
            try:
                self.obs.trace.dump_on_failure(
                    os.path.join(self._workdir, "trace_dump.json"),
                    reason=f"poll-loop crash: {exc!r}")
            except OSError:
                pass

    def _busy(self) -> bool:
        with self._lock:
            return bool(any(self._submitq)
                        or any(len(q) for q in self.cluster.pending)
                        or self._waiter_count()
                        # queued reads need steps to confirm/serve —
                        # keep the loop running until they resolve
                        or (self.cluster.reads is not None
                            and self.cluster.reads.pending_count())
                        # in-flight transactions decide off the
                        # finish() tail — keep stepping until then
                        or (self.cluster.txn is not None
                            and self.cluster.txn.wants_serial()))

    # holds-lock: _lock
    def _waiter_count(self) -> int:
        """Blocked commit waiters across replicas (caller holds
        ``_lock``); the sharded driver counts its per-group deques."""
        return sum(len(rt.inflight) for rt in self.runtimes)

    def _pipeline_ready(self) -> bool:
        """True iff the next iteration may DISPATCH WITHOUT FINISHING —
        the stable-leader traffic path where pipelining is a pure
        latency/throughput transform. Everything else (elections,
        admin requests, recovery, rebase drains, idle heartbeats)
        drains the pipeline and runs the serial ``step()``."""
        if (self._recover_req is not None or self._reset_req is not None
                or self._ckpt_req is not None):
            return False
        c = self.cluster
        if c.last is None or self._leader_view < 0:
            return False
        if c.need_recovery or self.stepped_down:
            return False
        # a membership change in flight polls device-side config state
        # every step — drive it through drained serial steps
        if self._config_phase is not None:
            return False
        # a due repair action needs the drained serial path (snapshot
        # install + redigest are state surgery); pipelining re-engages
        # the iteration after the repair completes
        if self.repair is not None and self.repair.needs_drain():
            return False
        # stop dispatching once the i32-rollover threshold is crossed:
        # the rebase is deferred until the pipeline drains, and the
        # headroom margin covers only boundedly many in-flight bursts
        if int(c.last["end"].max()) >= self.cfg.rebase_threshold:
            return False
        # an in-flight transaction holds the commit lane: votes and
        # decision records ride SERIAL dispatches only (the same
        # give-way rule elections and repair follow)
        if c.txn is not None and c.txn.wants_serial():
            return False
        # an open topology transition window runs its passes on the
        # drained serial path every iteration (seed → freeze →
        # cutover) — hold pipelining for the whole window
        topo = getattr(c, "topology", None)
        if topo is not None and topo.needs_drain():
            return False
        # the governor engages/disengages depth-D pipelining: until
        # backlog has STOOD for engage_evals (or while shedding), the
        # serial path acks a commit one dispatch sooner
        if (self.governor is not None
                and not self.governor.decision.pipeline):
            return False
        # pipelining pays off only while APPEND BATCHES flow (encode
        # k+1 while k runs); with just blocked waiters and an empty
        # queue the serial loop acks a commit one dispatch sooner —
        # keeping the latency-bound regime on the serial path is what
        # makes pipelining a pure win, not a latency trade
        with self._lock:
            if not (any(self._submitq) or self._backlog()):
                return False
        # any expired follower election timer needs the serial path
        # (bursts and pipelined steps never fire timeouts)
        last = c.last
        for r, rt in enumerate(self.runtimes):
            if (not self._role_is_leader(last, r)
                    and rt.timer.expired()):
                return False
        return True

    def _role_is_leader(self, res, r: int) -> bool:
        return bool(res["role"][r] == int(Role.LEADER))

    # ------------------------------------------------------------------
    # idle quiescence (the PR 8 idle-dispatch bias, closed at source)
    # ------------------------------------------------------------------

    def _repair_idle(self) -> bool:
        """True iff the repair pipeline has nothing in flight: no due
        drain, no owned recoveries, no replica held in quarantine or
        probation (held replicas need steps to advance their
        hysteresis)."""
        if self.repair is None:
            return True
        if self.repair.needs_drain() or self.repair.owned():
            return False
        return not self._repair_held_any()

    def _repair_held_any(self) -> bool:
        return bool(self.repair.blocked_replicas(0))

    def _idle_margin(self) -> float:
        """Seconds until the earliest follower election timer would
        fire. The idle loop must dispatch a heartbeat step well before
        that — each step carries the heartbeat, so stepping IS the
        beat. The sharded driver overrides this: its group timers are
        step-domain and only tick for leaderless groups, which the
        skip gate already excludes."""
        last = self.cluster.last
        m = float("inf")
        for r, rt in enumerate(self.runtimes):
            if self._role_is_leader(last, r):
                continue
            m = min(m, rt.timer.remaining())
        return m

    def _can_idle_skip(self) -> bool:
        """True iff this iteration may skip the device dispatch
        entirely: a led, healthy, traffic-free cluster with no admin /
        repair / config work due and every follower election timer
        comfortably far from firing. Conservative by construction —
        any doubt dispatches the step."""
        if not self._idle_quiesce:
            return False
        c = self.cluster
        if c.last is None or self._leader_view < 0:
            return False
        # chaos drills (attached link models) own their own timing —
        # getattr both ways: SimCluster has link_model, ShardedCluster
        # has a per-group link_models dict
        if (getattr(c, "link_model", None) is not None
                or getattr(c, "link_models", None)):
            return False
        # an active profiler capture wants the serving path visible
        if self.profile_session is not None and self.profile_session.active:
            return False
        with self._lock:
            if (self._recover_req is not None
                    or self._reset_req is not None
                    or self._ckpt_req is not None):
                return False
        if self._config_phase is not None:
            return False
        if c.need_recovery or self.stepped_down:
            return False
        if not self._repair_idle():
            return False
        if self._busy():
            return False
        return self._idle_margin() > self._idle_guard

    def _idle_park(self) -> None:
        """One idle-quiescence beat: count the avoided dispatch, keep
        the alert/health cadences fresh, and park with exponential
        backoff — bounded well inside the follower-timer margin, and
        broken instantly by any intake event (``_wake``)."""
        self.obs.metrics.inc("idle_dispatches_avoided_total")
        if self._idle_backoff <= 0.001:
            # once per quiescence episode, not per beat
            self.obs.trace.record(obs_trace.IDLE_QUIESCE)
        self._cadence_observe()
        wait = min(self._idle_backoff, self._idle_margin() / 2)
        self._idle_backoff = min(self._idle_backoff * 2,
                                 self._idle_backoff_max)
        self._wake.wait(timeout=max(wait, 0.0005))
        self._wake.clear()

    def _drain_pipeline(self) -> bool:
        """Block until the readback thread retired every in-flight
        ticket (device outputs read AND post-step host rules run).
        True when drained; False when the loop died."""
        with self._pl_cv:
            while self._pl_pending:
                if self.loop_error is not None:
                    return False
                if (self._rb_thread is not None
                        and not self._rb_thread.is_alive()):
                    return False
                self._pl_cv.wait(timeout=0.05)
        return self.loop_error is None

    def _readback_loop(self) -> None:
        """Consumer half of the pipelined driver: finish tickets in
        dispatch (FIFO) order and run every post-step host rule —
        including observability export — OFF the dispatch path."""
        while True:
            ticket = self._pl_queue.get()
            if ticket is None:
                return
            try:
                res = self.cluster.finish(ticket)
                self._post_step(res)
            except Exception as exc:  # noqa: BLE001
                self._handle_loop_crash(exc)
                with self._pl_cv:
                    self._pl_pending = 0
                    self._pl_cv.notify_all()
                return
            with self._pl_cv:
                self._pl_pending -= 1
                self._pl_cv.notify_all()

    def _dispatch_loop(self, period: float) -> None:
        while not self._stop.is_set():
            if self.loop_error is not None:
                return
            if not (self.pipeline >= 2 and self._pipeline_ready()):
                # serial iteration (elections / admin / recovery /
                # rebase / idle heartbeat): drain first — the engine's
                # FIFO finish contract forbids a fused step() while
                # tickets are in flight
                if not self._drain_pipeline():
                    return
                if self._stop.is_set():
                    return
                # the idle-skip check and the step share one crash
                # handler: a raised skip-path bug must fail blocked
                # waiters loudly, never park the loop dead silently
                try:
                    if self._can_idle_skip():
                        # idle quiescence: nothing needs the device —
                        # skip the dispatch, keep the cadences live
                        self._idle_park()
                        continue
                    self._idle_backoff = 0.001  # re-arm the backoff
                    self.step()
                except Exception as exc:  # noqa: BLE001
                    self._handle_loop_crash(exc)
                    return
                if not self._busy() and period:
                    self._wake.wait(timeout=period)
                self._wake.clear()
                continue
            # ---- pipelined fast path: encode + dispatch only ----
            with self._pl_cv:
                if self._pl_pending >= self.pipeline:
                    self._pl_cv.wait(timeout=0.05)
                    continue
            self._pump_submitq()
            dec = (self.governor.decision if self.governor is not None
                   else None)
            if (dec is not None and dec.coalesce_us > 0
                    and self._backlog()):
                # bounded admission-coalescing wait (governor): at a
                # high arrival rate with a window still filling, a
                # beat of patience ships fuller windows — strictly
                # bounded, never applied while shedding
                time.sleep(dec.coalesce_us / 1e6)
                self.obs.metrics.observe(
                    "governor_coalesce_us", dec.coalesce_us,
                    buckets=LATENCY_BUCKETS_US)
                self._pump_submitq()
            try:
                self._timer_obs.start("device_step")
                # dec.max_k can flip to 1 (SLO shed) between
                # _pipeline_ready and here: honor it with a no-take
                # heartbeat dispatch — never a burst; the next
                # iteration sees pipeline disengaged and drains to
                # the serial path
                if self._backlog() and (dec is None or dec.max_k > 1):
                    ticket = self.cluster.begin_burst(
                        max_k=dec.max_k if dec is not None else None)
                else:
                    # waiters with empty queues: quorum/commit trails
                    # the last append by a step — advance it (no batch
                    # take: pipelined appends ride capacity-clamped
                    # bursts only, so shortfall requeues cannot reorder
                    # against in-flight dispatches)
                    ticket = self.cluster.begin_step(take_batch=False)
                self._timer_obs.stop("device_step")
            except Exception as exc:  # noqa: BLE001
                self._handle_loop_crash(exc)
                return
            with self._pl_cv:
                self._pl_pending += 1
            self._pl_queue.put(ticket)

    def run(self, period: float = 0.0) -> None:
        """Run the polling loop in background threads. While client work
        is pending or blocked app threads await commit, the loop
        free-runs (the reference's busy commit loop). When idle it
        PARKS for up to ``period`` seconds (the hb_period cadence — each
        step carries the heartbeat, so ``period`` must stay well under
        the election timeout) and wakes INSTANTLY when a link thread
        hands it an event — on a shared-core host, idle free-running
        would steal the CPU the app itself needs.

        With ``pipeline >= 2`` (the default) the stable-leader traffic
        path runs DOUBLE-BUFFERED: the dispatch thread encodes and
        enqueues batch k+1 while batch k is still running on the
        device, and the readback thread blocks on outputs and runs the
        post-step host rules (requeue, replay, acks, observability) —
        ``device_sync`` never blocks the enqueue path. ``pipeline=0``
        (or 1) restores the fully serial loop."""
        self._pl_pending = 0
        self._rb_thread = threading.Thread(target=self._readback_loop,
                                           daemon=True)
        self._rb_thread.start()

        def loop():
            try:
                self._dispatch_loop(period)
            finally:
                self._pl_queue.put(None)     # retire the readback side
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def prewarm(self) -> None:
        """AOT-warm every step variant and burst tier so the first loaded
        round never eats a multi-second JIT pause mid-serving."""
        self.cluster.prewarm()

    def stop(self, join_timeout: float = 5.0) -> None:
        # idempotent: tests (and death-path drills) may stop explicitly
        # and again from fixture teardown — the second call must not
        # touch already-closed native handles
        if getattr(self, "_stopped", False):
            return
        self._stop.set()
        self._wake.set()
        # the ops exporter and series log are independent of the poll
        # thread — close them first so a wedged loop still leaves a
        # flushed series.jsonl and a closed port behind
        if self.exporter is not None:
            self.exporter.close()
        if self.series is not None:
            self.series.close()
        with self._pl_cv:
            self._pl_cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            if self._thread.is_alive():
                # a wedged poll thread (e.g. blocked inside a device
                # step) may still be touching the native handles:
                # closing them under it would be a use-after-free.
                # Leak them loudly instead; a later stop() retries.
                # But FIRST fail every blocked commit waiter: with
                # _stop set no step will ever release them, so app
                # threads parked in proxy_call commit waits would hang
                # forever instead of failing fast with -1 (releasing a
                # PendingEvent is pure host state — safe regardless of
                # what the wedged thread is doing; a concurrent release
                # from it is an idempotent no-op) — ADVICE.md #4.
                if self.cluster.reads is not None:
                    self.cluster.reads.fail_all(
                        "stop (wedged poll thread)")
                if self.cluster.streams is not None:
                    self.cluster.streams.fail_all(
                        "stop (wedged poll thread)")
                with self._lock:
                    n = sum(len(rt.inflight) for rt in self.runtimes)
                    for rt in self.runtimes:
                        self._fail_inflight_locked(
                            rt, "stop (wedged poll thread)")
                self.obs.trace.record(obs_trace.STOP_FORCED,
                                      released=n)
                if self._workdir is not None:
                    try:
                        self.obs.trace.dump_on_failure(
                            os.path.join(self._workdir,
                                         "trace_dump.json"),
                            reason="stop: wedged poll thread")
                    except OSError:
                        pass
                self.runtimes[0].log.info_wtime(
                    "STOP: poll thread did not exit within %gs; "
                    "released %d inflight waiters with -1; leaving "
                    "native handles open" % (join_timeout, n))
                return
        if self._rb_thread is not None:
            self._pl_queue.put(None)
            self._rb_thread.join(timeout=join_timeout)
        # release commit waiters that were already inflight at stop —
        # nothing will ever step again, so they must fail, not hang
        # (queued reads the same: no step will ever confirm them)
        if self.cluster.reads is not None:
            self.cluster.reads.fail_all("stop")
        # watchers/scans the same: the pump must quiesce and every
        # blocked subscriber poll must fail fast (clients resume
        # elsewhere with their tokens); flushes the CDC sink
        if self.cluster.streams is not None:
            self.cluster.streams.fail_all("stop")
        with self._lock:
            for rt in self.runtimes:
                self._fail_inflight_locked(rt, "stop")
        try:
            for rt in self.runtimes:
                # one replica's close failure must not leak the rest
                for res in (rt.proxy, rt.replay, rt.store, rt.log):
                    if res is None:
                        continue
                    try:
                        res.close()
                    except OSError:
                        pass
        finally:
            # latch only after the cleanup actually ran
            self._stopped = True

    def leader(self) -> int:
        with self._lock:
            return self._leader_view

    # ------------------------------------------------------------------
    # the linearizable read queue (runtime/reads.py)
    # ------------------------------------------------------------------

    def read_replica(self, group: int = 0) -> int:
        """The replica a linearizable read should target: the group's
        lease-serving holder (zero-traffic path) when one exists, else
        the leader (read-index path), else replica 0 (the hub confirms
        before serving, so a bad default only costs latency)."""
        lm = self.cluster.leases
        r = lm.serving_holder(group) if lm is not None else -1
        if r < 0:
            r = self.leader()
        return r if r >= 0 else 0

    def read(self, fn=None, *, replica: Optional[int] = None,
             group: int = 0, timeout: float = 30.0):
        """Queue one linearizable read and block until it serves (or
        fails). ``fn()`` runs AT the linearization point — on the
        readback thread, against the serving replica's applied state —
        and its return value lands on the returned ticket. Reads never
        enter ``begin_*``/``finish`` and never consume ring slots; an
        idle loop is woken so the confirming step dispatches
        immediately."""
        hub = self.cluster.reads
        if hub is None:
            raise RuntimeError(
                "driver was built with leases=False — no read path")
        if replica is None:
            replica = self.read_replica(group)
        t = hub.submit(fn, replica=replica, group=group)
        self._wake.set()
        t.wait(timeout)
        return t

    def can_serve_read(self, r: int) -> bool:
        """Read-index check: True iff replica ``r`` verified its
        leadership against a majority on the latest step, so a read of
        state at its commit index is linearizable (the reference verifies
        before answering pending reads — ep_dp_reply_read_req,
        dare_ep_db.c:132-161)."""
        last = self.cluster.last
        return (last is not None
                and bool(last["leadership_verified"][r]))
