"""Deterministic in-process multi-replica cluster — the test/bench harness.

The reference validates only end-to-end on a real IB cluster (SURVEY.md §4);
this harness runs the full protocol (election, replication, commit, pruning,
reconfig, partitions) deterministically on one host: N replicas are either N
rows of a ``vmap``-simulated axis (``mode="sim"``, any single device) or one
per device of a real mesh (``mode="spmd"``, shard_map).

Partitions/crashes are expressed through per-replica ``peer_mask`` rows —
the analog of ``reconf_bench.sh`` killing processes, but reproducible.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rdma_paxos_tpu.config import LogConfig, REBASE_STALL_STEPS
from rdma_paxos_tpu.consensus.log import (
    EntryType, M_CONN, M_GIDX, M_LEN, M_REQID, M_TYPE, META_W)
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.consensus.step import StepInput, fetch_window
from rdma_paxos_tpu.parallel.mesh import (
    build_sim_burst, build_sim_step, build_spmd_burst, build_spmd_step,
    make_replica_mesh, stack_states)
from rdma_paxos_tpu.utils.codec import bytes_to_words


# Compiled steps are shared across ALL cluster engines (same static
# config ⇒ same XLA program); without this every cluster re-traces the
# protocol. Module-level so the sharded multi-group engine
# (rdma_paxos_tpu.shard.cluster.ShardedCluster) and SimCluster share
# ONE cache — a G-group cluster and a single-group cluster built from
# the same LogConfig never compile the same program twice, and tests
# can assert cache-key sets across both engines.
STEP_CACHE: Dict[tuple, object] = {}


def assemble_frames(types, conns, lens, raw, idxs) -> bytes:
    """Store-ready framed blob for the client entries at ``idxs`` of a
    decoded window: ``([u32 len][u8 etype][u32 conn][payload])*``,
    assembled in two numpy passes (fill + ragged masked gather) — zero
    per-record Python on the store path. ONE implementation shared by
    SimCluster and ShardedCluster so the byte format can never drift
    between the engines (the G=1 parity contract)."""
    row = raw.shape[1]
    cl = lens[idxs].astype(np.uint32)
    mat = np.zeros((idxs.size, 9 + row), np.uint8)
    mat[:, 0:4] = (cl + 5).astype("<u4")[:, None].view(np.uint8)
    mat[:, 4] = types[idxs]
    mat[:, 5:9] = conns[idxs].astype("<i4")[:, None].view(np.uint8)
    mat[:, 9:] = raw[idxs]
    keep = (np.arange(9 + row, dtype=np.uint32)[None]
            < (9 + cl)[:, None])
    return mat[keep].tobytes()


class SimCluster:
    """N-replica protocol simulation with host-side bookkeeping."""

    # legacy alias (tests and callers key off the class attribute);
    # the SAME dict object as the module-level shared cache
    _STEP_CACHE: Dict[tuple, object] = STEP_CACHE

    def __init__(self, cfg: LogConfig, n_replicas: int,
                 group_size: Optional[int] = None, *, mode: str = "sim",
                 use_pallas: Optional[bool] = None,
                 interpret: bool = False,
                 fanout: str = "gather", stable_fast_path: bool = True,
                 audit: bool = False, flight_capacity: int = 64):
        self.cfg = cfg
        self.R = n_replicas
        self.group_size = group_size or n_replicas
        self._mode = mode
        # correctness observability (obs/audit.py): audit=True compiles
        # the digest-chain step variants (distinct cache keys — the
        # default programs are untouched), feeds every step's digest
        # windows to a cluster AuditLedger, and records a bounded
        # flight ring of step inputs/outputs for post-mortem dumps
        self._audit = audit
        if audit:
            from rdma_paxos_tpu.obs.audit import (
                AuditLedger, FlightRecorder)
            self.auditor = AuditLedger(n_replicas)
            self.flight = FlightRecorder(flight_capacity)
        else:
            self.auditor = None
            self.flight = None
        # production default: the Pallas quorum kernel on TPU (same code
        # path as the benches), jnp reference scan elsewhere
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self._use_pallas = use_pallas
        self._interpret = interpret
        self._fanout = fanout
        # dispatch the elections-free STABLE step on iterations where no
        # election timer fired (the latency hot path — Phase B statically
        # removed, one fewer collective); compiled lazily on first use
        self._stable_fast_path = stable_fast_path
        self.state = stack_states(cfg, n_replicas, self.group_size)
        if mode == "spmd":
            mkey = (cfg, n_replicas, "mesh")
            if mkey not in self._STEP_CACHE:
                self._STEP_CACHE[mkey] = make_replica_mesh(n_replicas)
            self.mesh = self._STEP_CACHE[mkey]
            self._step = self._build_step(elections=True)
            self.state = jax.device_put(
                self.state,
                jax.sharding.NamedSharding(
                    self.mesh, jax.sharding.PartitionSpec("replica")))
        else:
            self._step = self._build_step(elections=True)
        # all replicas' windows in ONE dispatch (the per-replica loop of
        # fetch+slice dispatches dominated the host replay path). The
        # REPLAY window is wider than the protocol window: a K-step
        # burst commits up to K*batch_slots entries at once, and each
        # fetch dispatch costs host time — sweep in big gulps.
        self._replay_W = min(cfg.n_slots // 2,
                             max(4 * cfg.window_slots, 256))
        self._fetch_all = jax.jit(jax.vmap(
            lambda log, start: fetch_window(
                log, start, window_slots=self._replay_W)))
        # host bookkeeping
        self.applied = np.zeros(n_replicas, np.int64)   # host apply cursor
        self.peer_mask = np.ones((n_replicas, n_replicas), np.int32)
        self.pending: List[List[Tuple[int, int, int, bytes]]] = [
            [] for _ in range(n_replicas)]
        self._inflight: List[List[Tuple[int, int, int, bytes]]] = [
            [] for _ in range(n_replicas)]
        self.last: Optional[Dict[str, np.ndarray]] = None
        # (type, conn_id, req_id, payload) per replica, in apply order
        self.replayed: List[List[Tuple[int, int, int, bytes]]] = [
            [] for _ in range(n_replicas)]
        # store-ready framed blobs (([u32 len][etype][conn][payload])*)
        # built VECTORIZED during the window decode — the driver hands
        # them to StableStore.append_framed untouched. Only produced
        # when a consumer opts in (collect_frames), so pure-sim tests
        # don't accumulate them.
        self.collect_frames = False
        self.frames: List[List[bytes]] = [[] for _ in range(n_replicas)]
        # replicas whose log was force-pruned past their apply cursor
        # (force_log_pruning left them behind): replay stops — recycled
        # slots must never reach the app — until snapshot recovery
        self.need_recovery: set = set()
        self._wedged: set = set()     # test hook: frozen apply (wedged app)
        # coordinated i32-offset rollovers performed (see _maybe_rebase)
        self.rebases = 0
        self.rebased_total = 0
        # rebase-stall surfacing (ADVICE.md #3): a heard-but-lagging
        # row's low head pins the agreed delta at 0, so end marches
        # toward the i32 ceiling with no rollover possible. Consecutive
        # post-threshold steps with delta 0 are counted; past
        # REBASE_STALL_STEPS each further step increments
        # ``rebase_stalled`` (and the attached registry's counter), and
        # the transition emits one ``rebase_stalled`` trace event
        # (re-armed by the next successful rollover).
        self.rebase_stall_steps = 0
        self.rebase_stalled = 0
        # host-side observability facade (rdma_paxos_tpu.obs); attached
        # by ClusterDriver (or tests). NEVER read inside jitted code —
        # instrumentation must not change compiled-step cache keys.
        self.obs = None
        # optional obs.spans.StepPhaseProfiler: attributes step wall
        # time to phases (host encode / device dispatch / optional
        # fenced device sync / quorum-wait readback / apply). Host-side
        # only; with fence off it never blocks and never imports jax.
        self.profiler = None
        # pluggable per-link fault model (rdma_paxos_tpu.chaos.faults
        # .LinkModel): when attached, each step's peer_mask INPUT is
        # rewritten host-side into the effective hear-matrix
        # (asymmetric breaks, seeded drop/delay/dup, crashed
        # replicas). Purely a data rewrite — compiled-step cache keys
        # are unchanged (tests/test_chaos.py guards it). step_index is
        # the logical clock the model's per-step randomness keys on.
        self.link_model = None
        self.step_index = 0

    # ---------------- client-side API ----------------

    def submit(self, replica: int, payload: bytes,
               etype: EntryType = EntryType.SEND, conn: int = 1,
               req_id: int = 0) -> None:
        """Queue a client entry for the next step on `replica` (it only
        enters the log if that replica is leader — proxy semantics)."""
        self.pending[replica].append((int(etype), conn, req_id, payload))

    def partition(self, groups: Sequence[Sequence[int]]) -> None:
        """Split the cluster: replicas hear only same-group peers."""
        if self._fanout == "psum":
            # the O(W) psum fan-out assumes at most one self-claimed
            # leader (full connectivity); two partitioned leaders would
            # SUM their windows into followers' logs — reject loudly
            # (see replica_step's fanout docstring)
            raise ValueError(
                "partitions cannot be modeled with fanout='psum'; "
                "build the cluster with fanout='gather'")
        self.peer_mask[:] = 0
        for g in groups:
            for i in g:
                for j in g:
                    self.peer_mask[i, j] = 1
        np.fill_diagonal(self.peer_mask, 1)

    def heal(self) -> None:
        self.peer_mask[:] = 1

    def wedge_apply(self, r: int) -> None:
        """Freeze replica ``r``'s apply progress (models a wedged app:
        the host stops consuming committed entries while the replica
        keeps acking windows)."""
        self._wedged.add(r)

    def unwedge_apply(self, r: int) -> None:
        self._wedged.discard(r)

    # ---------------- stepping ----------------

    def _effective_mask(self):
        """The step's hear-matrix: the base peer_mask, refined by the
        attached link model (host-side only; psum fan-out still
        requires the EFFECTIVE mask to be full)."""
        if self.link_model is None:
            return self.peer_mask
        return self.link_model.effective_mask(self.peer_mask,
                                              self.step_index)

    def _build_inputs(self, timeouts: Sequence[int]) -> StepInput:
        cfg, R = self.cfg, self.R
        mask = self._effective_mask()
        if self._fanout == "psum" and not mask.all():
            raise ValueError(
                "psum fan-out requires full connectivity; use "
                "fanout='gather' to model partitions")
        B = cfg.batch_slots
        data = np.zeros((R, B, cfg.slot_words), np.int32)
        meta = np.zeros((R, B, META_W), np.int32)
        count = np.zeros((R,), np.int32)
        for r in range(R):
            take = self.pending[r][:B]
            self.pending[r] = self.pending[r][B:]
            self._inflight[r] = take
            for i, (t, conn, req, payload) in enumerate(take):
                data[r, i] = bytes_to_words(payload, cfg.slot_words)
                meta[r, i, M_TYPE] = t
                meta[r, i, M_CONN] = conn
                meta[r, i, M_REQID] = req
                meta[r, i, M_LEN] = len(payload)
            count[r] = len(take)
        tmo = np.zeros((R,), np.int32)
        for r in timeouts:
            tmo[r] = 1
        return StepInput(
            batch_data=jnp.asarray(data),
            batch_meta=jnp.asarray(meta),
            batch_count=jnp.asarray(count),
            timeout_fired=jnp.asarray(tmo),
            peer_mask=jnp.asarray(mask),
            apply_done=jnp.asarray(self.applied.astype(np.int32)),
            queue_depth=jnp.asarray(
                np.array([len(q) for q in self.pending], np.int32)),
        )

    # burst size tiers: the smallest tier >= the steps needed is compiled
    # (bounded recompiles) and padded with zero-count steps
    K_TIERS = (2, 4, 8, 16)

    def _burst_fn(self, K: int):
        # the "audit" marker is appended ONLY when auditing: default
        # clusters' cache keys are bit-identical to the pre-audit ones
        # (tests/test_audit.py guards exactly this)
        key = (self.cfg, self.R, self._mode, self._use_pallas,
               self._interpret, self._fanout, "burst", K) \
            + (("audit",) if self._audit else ())
        fn = self._STEP_CACHE.get(key)
        if fn is None:
            if self._mode == "spmd":
                fn = build_spmd_burst(self.cfg, self.R, self.mesh,
                                      use_pallas=self._use_pallas,
                                      interpret=self._interpret,
                                      fanout=self._fanout,
                                      audit=self._audit)
            else:
                fn = build_sim_burst(self.cfg, self.R,
                                     use_pallas=self._use_pallas,
                                     interpret=self._interpret,
                                     fanout=self._fanout,
                                     audit=self._audit)
            self._STEP_CACHE[key] = fn
        return fn

    def step_burst(self) -> Dict[str, np.ndarray]:
        """Drain the pending queues through up to ``max(K_TIERS)`` fused
        protocol steps in ONE device dispatch (multi-step driver mode —
        the host-side analog of the reference's busy commit loop). No
        election timeouts fire inside the burst; the caller must only
        burst while a leader is known. Returns the final step's outputs
        (``accepted`` aggregated over the burst)."""
        cfg, R, B = self.cfg, self.R, self.cfg.batch_slots
        assert self.last is not None, "burst requires a stepped cluster"
        prof = self.profiler
        if prof is not None:
            prof.start("host_encode")
        # capacity sizing: never enqueue more than the ring can take
        # without drops, so mid-burst drops (which would reorder a
        # connection's fragments against later steps) cannot occur
        take_n = []
        for r in range(R):
            avail = (cfg.n_slots - 1) - (int(self.last["end"][r])
                                         - int(self.last["head"][r]))
            take_n.append(min(len(self.pending[r]), max(avail, 0),
                              self.K_TIERS[-1] * B))
        k_needed = max(1, max(-(-n // B) for n in take_n))
        K = next(k for k in self.K_TIERS if k >= k_needed)

        data = np.zeros((K, R, B, cfg.slot_words), np.int32)
        meta = np.zeros((K, R, B, META_W), np.int32)
        count = np.zeros((K, R), np.int32)
        taken: List[List[Tuple[int, int, int, bytes]]] = []
        for r in range(R):
            take = self.pending[r][:take_n[r]]
            self.pending[r] = self.pending[r][take_n[r]:]
            taken.append(take)
            for i, (t, conn, req, payload) in enumerate(take):
                k, j = divmod(i, B)
                data[k, r, j] = bytes_to_words(payload, cfg.slot_words)
                meta[k, r, j, M_TYPE] = t
                meta[k, r, j, M_CONN] = conn
                meta[k, r, j, M_REQID] = req
                meta[k, r, j, M_LEN] = len(payload)
            for k in range(K):
                count[k, r] = max(0, min(take_n[r] - k * B, B))

        # one effective mask covers the whole fused burst (the link
        # model's granularity is a dispatch, not an inner step); the
        # logical clock still advances by K so per-step randomness
        # never replays across dispatches
        mask = self._effective_mask()
        if self._fanout == "psum" and not mask.all():
            raise ValueError(
                "psum fan-out requires full connectivity; use "
                "fanout='gather' to model partitions")
        fn = self._burst_fn(K)
        if prof is not None:
            prof.stop("host_encode")
            prof.start("device_dispatch")
        self.state, outs = fn(self.state, jnp.asarray(data),
                              jnp.asarray(meta), jnp.asarray(count),
                              jnp.asarray(mask),
                              jnp.asarray(self.applied.astype(np.int32)),
                              jnp.asarray(np.array(
                                  [len(q) for q in self.pending],
                                  np.int32)))
        if prof is not None:
            prof.stop("device_dispatch")
            prof.sync(outs)             # fenced device_sync (opt-in)
            prof.start("quorum_wait")
        res = {k: np.asarray(getattr(outs, k))[-1]
               for k in ("term", "role", "leader_id", "voted_term",
                         "voted_for", "head", "apply", "commit", "end",
                         "hb_seen", "became_leader", "acked",
                         "peer_acked", "leadership_verified",
                         "rebase_delta")}
        acc = np.asarray(outs.accepted).sum(axis=0)         # [R]
        res["accepted"] = acc
        if prof is not None:
            prof.stop("quorum_wait")
        if self._audit:
            # each fused step emitted its own digest window: ingest
            # them in order so the tiling property (no gaps) holds
            a_s = np.asarray(outs.audit_start)      # [K, R]
            a_d = np.asarray(outs.audit_digest)     # [K, R, W]
            a_t = np.asarray(outs.audit_term)       # [K, R, W]
            a_c = np.asarray(outs.commit)           # [K, R]
            for k in range(a_s.shape[0]):
                self._ingest_audit(a_s[k], a_d[k], a_t[k], a_c[k])
            res["audit_start"], res["audit_digest"] = a_s[-1], a_d[-1]
            res["audit_term"] = a_t[-1]
        # Shortfall: appends stop entirely the step the replica is not
        # leader and the capacity clamp drops suffixes only, so the
        # appended set is always a PREFIX of ``taken`` — requeue the
        # remainder in order, exactly like step() does (never raise:
        # this runs on the poll thread). A replica deposed mid-burst
        # drops its remainder by design, mirroring step()'s non-leader
        # rule — the driver fails the blocked events so clients retry
        # against the new leader.
        for r in range(R):
            if taken[r] and res["role"][r] == int(Role.LEADER):
                a = int(acc[r])
                self._stamp_appends(r, taken[r], a, res)
                if a < len(taken[r]):
                    self.pending[r] = taken[r][a:] + self.pending[r]
        if prof is not None:
            prof.start("apply")
        self._replay_committed(res)
        if prof is not None:
            prof.stop("apply")
        if self._audit:
            self._record_flight(res, taken, (), burst_k=K)
        self._maybe_rebase(res)
        self.last = res
        self.step_index += K
        self._observe_spans(res)
        return res

    def _build_step(self, *, elections: bool):
        """Compile (or fetch cached) the protocol step for this cluster's
        static config — the single source for both the full and stable
        variants, so they can never drift apart in build flags."""
        key = (self.cfg, self.R, self._mode, self._use_pallas,
               self._interpret, self._fanout, elections) \
            + (("audit",) if self._audit else ())
        cached = self._STEP_CACHE.get(key)
        if cached is None:
            kw = dict(use_pallas=self._use_pallas,
                      interpret=self._interpret, fanout=self._fanout,
                      elections=elections, audit=self._audit)
            if self._mode == "spmd":
                cached = build_spmd_step(self.cfg, self.R, self.mesh, **kw)
            else:
                cached = build_sim_step(self.cfg, self.R, **kw)
            self._STEP_CACHE[key] = cached
        return cached

    def prewarm(self, tiers: Optional[Sequence[int]] = None) -> None:
        """Compile every step variant and burst tier up front (on copies
        of the live state — donation would otherwise consume it). A
        first-use JIT pause of seconds mid-serving stalls the whole
        commit pipeline; paying it before traffic starts keeps the
        serving path pause-free."""
        cfg, R, B = self.cfg, self.R, self.cfg.batch_slots
        inp = StepInput(
            batch_data=jnp.zeros((R, B, cfg.slot_words), jnp.int32),
            batch_meta=jnp.zeros((R, B, META_W), jnp.int32),
            batch_count=jnp.zeros((R,), jnp.int32),
            timeout_fired=jnp.zeros((R,), jnp.int32),
            peer_mask=jnp.asarray(self.peer_mask),
            apply_done=jnp.zeros((R,), jnp.int32),
            queue_depth=jnp.zeros((R,), jnp.int32))
        for elections in (True, False):
            fn = self._build_step(elections=elections)
            st = jax.tree.map(lambda x: x.copy(), self.state)
            fn(st, inp)
        pm = jnp.asarray(self.peer_mask)
        ap = jnp.zeros((R,), jnp.int32)
        for K in (tiers if tiers is not None else self.K_TIERS):
            fn = self._burst_fn(K)
            st = jax.tree.map(lambda x: x.copy(), self.state)
            fn(st, jnp.zeros((K, R, B, cfg.slot_words), jnp.int32),
               jnp.zeros((K, R, B, META_W), jnp.int32),
               jnp.zeros((K, R), jnp.int32), pm, ap,
               jnp.zeros((R,), jnp.int32))

    def step(self, timeouts: Sequence[int] = ()) -> Dict[str, np.ndarray]:
        timeouts = list(timeouts)       # may be a one-shot iterable
        prof = self.profiler
        if prof is not None:
            prof.start("host_encode")
        inp = self._build_inputs(timeouts)
        # no timer fired ⟹ Phase B is provably a no-op: dispatch the
        # stable step (bit-identical outputs, one fewer collective)
        fn = (self._build_step(elections=False)
              if self._stable_fast_path and not timeouts
              else self._step)
        if prof is not None:
            prof.stop("host_encode")
            prof.start("device_dispatch")
        self.state, out = fn(self.state, inp)
        if prof is not None:
            prof.stop("device_dispatch")
            prof.sync(out)              # fenced device_sync (opt-in)
            prof.start("quorum_wait")
        res = {k: np.asarray(getattr(out, k))
               for k in ("term", "role", "leader_id", "voted_term",
                         "voted_for", "head", "apply",
                         "commit", "end", "hb_seen", "became_leader",
                         "acked", "accepted", "peer_acked",
                         "leadership_verified", "rebase_delta")}
        if prof is not None:
            prof.stop("quorum_wait")
        if self._audit:
            # after the quorum_wait stop: audit host work must not
            # inflate the PR3 phase attribution it sits next to
            for k in ("audit_start", "audit_digest", "audit_term"):
                res[k] = np.asarray(getattr(out, k))
            # ingest BEFORE _maybe_rebase: the emitted indices are raw
            # (pre-rollover), consistent with the current rebased_total
            self._ingest_audit(res["audit_start"], res["audit_digest"],
                               res["audit_term"], res["commit"])
            flight_taken = [list(t) for t in self._inflight]
        # ring-full backpressure: entries the leader could not append are
        # requeued in order (submissions to non-leaders are dropped by
        # design — proxy submits on the leader only)
        for r in range(self.R):
            take = self._inflight[r]
            self._inflight[r] = []
            if take and res["role"][r] == int(Role.LEADER):
                acc = int(res["accepted"][r])
                self._stamp_appends(r, take, acc, res)
                if acc < len(take):
                    self.pending[r] = take[acc:] + self.pending[r]
        if prof is not None:
            prof.start("apply")
        self._replay_committed(res)
        if prof is not None:
            prof.stop("apply")
        if self._audit:
            self._record_flight(res, flight_taken, timeouts)
        self._maybe_rebase(res)
        self.last = res
        self.step_index += 1
        self._observe_spans(res)
        return res

    # ------------------------------------------------------------------
    # silent-divergence auditing (obs/audit.py; audit=True clusters)
    # ------------------------------------------------------------------

    def _ingest_audit(self, starts, digests, terms, commits) -> None:
        """Feed one step's per-replica digest windows to the ledger,
        converted to ABSOLUTE indices (raw + rebased_total — callers
        run this before _maybe_rebase so the two stay consistent)."""
        led = self.auditor
        led.obs = self.obs              # pick up a late-attached facade
        W = self.cfg.window_slots
        reb = self.rebased_total
        s_l, c_l = starts.tolist(), commits.tolist()
        for r in range(self.R):
            start, commit = s_l[r], c_l[r]
            n = commit - start
            if n <= 0:
                continue
            off = start - (commit - W)
            led.record_window(r, start + reb,
                              digests[r, off:off + n],
                              terms[r, off:off + n], commit + reb,
                              step=self.step_index)

    def _record_flight(self, res, taken, timeouts,
                       burst_k: int = 1) -> None:
        """One flight-recorder entry per dispatch: the step's inputs
        (per-replica submitted batches), scalar outputs, host apply
        cursors, and per-replica digest heads — raw offsets plus the
        rebased_total in force, so the dump is self-describing.
        Values stay numpy arrays / payload bytes (fresh per step,
        copied where a later in-place mutation could reach them); the
        recorder converts to plain JSON data at dump time only."""
        entry = dict(
            step=self.step_index, burst_k=burst_k,
            timeouts=[int(t) for t in timeouts],
            rebased_total=int(self.rebased_total),
            inputs=taken,
            outputs={k: res[k].copy()
                     for k in ("term", "role", "leader_id", "head",
                               "apply", "commit", "end", "accepted")},
            applied=self.applied.copy(),
            digests=dict(start=res["audit_start"].copy(),
                         commit=res["commit"].copy(),
                         window=res["audit_digest"]))
        self.flight.record(entry)

    # ------------------------------------------------------------------
    # span hooks (host-side causal tracing — obs.spans; all no-ops
    # when no recorder is attached or nothing is sampled)
    # ------------------------------------------------------------------

    def _span_recorder(self):
        from rdma_paxos_tpu.obs.spans import active_recorder
        return active_recorder(self.obs)

    def _stamp_appends(self, r: int, take, acc: int, res) -> None:
        """The accepted PREFIX of ``take`` landed at absolute indices
        ``[end-acc, end)`` on leader ``r`` — stamp each sampled span
        with its ``(term, index)`` correlation key."""
        spans = self._span_recorder()
        if spans is None or not spans.open_count or acc <= 0:
            return
        end_abs = int(res["end"][r]) + self.rebased_total
        term = int(res["term"][r])
        replicas = range(self.R)
        for i, (_t, conn, req, _p) in enumerate(take[:acc]):
            spans.stamp_append(conn, req, term, end_abs - acc + i, r,
                               replicas=replicas)

    def _observe_spans(self, res) -> None:
        """Advance every replica's commit/apply span frontiers (absolute,
        rebase-corrected — runs after ``_maybe_rebase`` so the offsets
        and ``rebased_total`` are mutually consistent)."""
        spans = self._span_recorder()
        if spans is None or not spans.open_count:
            return
        rebased = self.rebased_total
        for r in range(self.R):
            spans.commit_advance(r, int(res["commit"][r]) + rebased)
            spans.apply_advance(r, int(self.applied[r]) + rebased)

    # consecutive post-threshold zero-delta steps before the stall is
    # declared — shared with NodeDaemon (config.REBASE_STALL_STEPS)
    REBASE_STALL_STEPS = REBASE_STALL_STEPS

    def _rebase_stalled_step(self, res) -> None:
        """One post-threshold step passed with the rollover delta
        pinned at 0 — count it, and surface the stall once it persists
        (the i32 ceiling is approaching and nothing will fire)."""
        self.rebase_stall_steps += 1
        if self.rebase_stall_steps < self.REBASE_STALL_STEPS:
            return
        self.rebase_stalled += 1
        if self.obs is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.metrics.inc("rebase_stalled")
            if self.rebase_stall_steps == self.REBASE_STALL_STEPS:
                heads = [int(res["head"][r]) for r in range(self.R)]
                self.obs.trace.record(
                    _trace.REBASE_STALLED,
                    end_max=int(res["end"].max()),
                    threshold=self.cfg.rebase_threshold,
                    min_head=min(heads), heads=heads,
                    steps=self.rebase_stall_steps)

    def _maybe_rebase(self, res) -> None:
        """Coordinated i32-offset rollover (LogConfig.rebase_threshold):
        when any end offset crosses the threshold, subtract the minimum
        head from EVERY offset on every replica and from the host apply
        cursors — invisible to the protocol (offsets are relative), and
        it restores ~threshold entries of headroom. The in-process
        driver is omniscient, so the min is over ALL replicas (not just
        heard ones) — partition-safe: a partitioned laggard's low head
        simply defers the rollover until it recovers or is evicted.
        ``res`` is adjusted in place so callers observe post-rollover
        offsets."""
        if int(res["end"].max()) < self.cfg.rebase_threshold:
            return
        # the slot of global index g is g % n_slots and entries do NOT
        # move: the subtraction must preserve the mapping, so the delta
        # is the min head rounded DOWN to a multiple of n_slots. A
        # replica already flagged need_recovery is EXCLUDED from the
        # min: it stopped replaying (snapshot install renumbers it from
        # the donor), and letting its frozen head pin the rollover
        # would wedge the whole cluster at the i32 ceiling. Its offsets
        # may go transiently negative — benign: the gap gate keeps it
        # from absorbing windows until recovery overwrites them.
        heads = [int(res["head"][r]) for r in range(self.R)
                 if r not in self.need_recovery]
        if not heads:
            self._rebase_stalled_step(res)
            return
        delta = min(heads) & ~(self.cfg.n_slots - 1)
        if delta <= 0:
            self._rebase_stalled_step(res)
            return
        from rdma_paxos_tpu.consensus.snapshot import rebase_offsets
        self.state = rebase_offsets(self.state, delta)
        self.applied -= delta
        for k in ("head", "apply", "commit", "end"):
            res[k] = res[k] - delta
        # keep the returned dict self-consistent: audit_start is an
        # index too (the ledger already ingested pre-rollover)
        if "audit_start" in res:
            res["audit_start"] = res["audit_start"] - delta
        self.rebases += 1
        self.rebased_total += delta
        self.rebase_stall_steps = 0          # re-arm stall detection
        if self.obs is not None:
            from rdma_paxos_tpu.obs import trace as _trace
            self.obs.metrics.inc("rebases_total")
            self.obs.metrics.inc("rebased_entries_total", delta)
            self.obs.trace.record(_trace.REBASE_APPLIED, delta=delta,
                                  rebases=self.rebases)

    def _replay_committed(self, res) -> None:
        """Host apply loop: fetch newly committed entries from the device
        log and 'replay' them (tests record them; the real driver hands
        them to the proxy) — apply_committed_entries analog
        (dare_server.c:1815-1974). All replicas' windows ride ONE device
        dispatch per sweep."""
        W = self._replay_W
        # Force-pruned laggards: when the ring no longer PHYSICALLY holds
        # entry `applied` (a newer entry recycled its slot — possible
        # once forced pruning let appends run ahead of a wedged member's
        # apply), replaying would feed garbage to the app. The stamped
        # global index (M_GIDX) proves integrity: fetched-entry gidx ==
        # expected index, else flag for snapshot recovery and stop.
        # Being merely below `head` is NOT sufficient to flag — the
        # benign one-step lazy-push lag puts followers there routinely
        # while their slots are still intact.
        while True:
            todo = [r for r in range(self.R)
                    if r not in self._wedged
                    and r not in self.need_recovery
                    and self.applied[r] < int(res["commit"][r])]
            if not todo:
                return
            starts = jnp.asarray(self.applied.astype(np.int32))
            wd_all, wm_all = self._fetch_all(self.state.log, starts)
            wd_all, wm_all = np.asarray(wd_all), np.asarray(wm_all)
            for r in todo:
                commit = int(res["commit"][r])
                n = int(min(commit - self.applied[r], W))
                wd, wm = wd_all[r], wm_all[r]
                if n > 0 and int(wm[0, M_GIDX]) != self.applied[r]:
                    self.need_recovery.add(r)       # slot recycled
                    continue
                # vectorized window decode: one contiguous byte view +
                # one column read per field (the per-entry scalar
                # conversions dominated the replay path at high rates)
                types = wm[:n, M_TYPE]
                client = ((types >= int(EntryType.CONNECT))
                          & (types <= int(EntryType.CLOSE)))
                idxs = np.nonzero(client)[0]
                if idxs.size:
                    conns = wm[:n, M_CONN]
                    reqs = wm[:n, M_REQID]
                    lens = wm[:n, M_LEN]
                    raw = np.ascontiguousarray(
                        wd[:n]).view(np.uint8).reshape(n, -1)
                    row = raw.shape[1]
                    buf = raw.tobytes()
                    rep = self.replayed[r]
                    for j in idxs:
                        o = int(j) * row
                        rep.append((int(types[j]), int(conns[j]),
                                    int(reqs[j]),
                                    buf[o:o + int(lens[j])]))
                    if self.collect_frames:
                        self.frames[r].append(assemble_frames(
                            types, conns, lens, raw, idxs))
                self.applied[r] += n

    # ---------------- inspection ----------------

    def leader(self) -> int:
        assert self.last is not None
        ids = [r for r in range(self.R)
               if self.last["role"][r] == int(Role.LEADER)]
        return ids[0] if len(ids) == 1 else -1

    def run_until_elected(self, candidate: int, max_steps: int = 5) -> int:
        for _ in range(max_steps):
            res = self.step(timeouts=[candidate])
            if res["role"][candidate] == int(Role.LEADER):
                return candidate
        raise AssertionError("election did not converge")
